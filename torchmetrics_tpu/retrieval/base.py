"""RetrievalMetric base (reference ``src/torchmetrics/retrieval/base.py:43``).

TPU-native compute: instead of the reference's per-query Python loop
(``base.py:165-182``), queries are grouped, padded to a ``(Q, L_max)`` rectangle (shapes
rounded up to powers of two to bound recompiles) and the masked single-query kernel is vmapped
over the batch — one fused device program for all queries. The sort / group-id / scatter
pipeline runs ON DEVICE (``_group_stats`` / ``_build_rectangles``); only two scalars and the
final per-query values cross the device→host boundary, so compute cost no longer scales with
D2H bandwidth (the dominant term on tunneled accelerators).

State: three list states with ``dist_reduce_fx=None`` (gather-without-reduce,
reference ``base.py:130-132``).

Streaming sketch mode (``approx="sketch"``, docs/sketches.md): instead of keeping every
``(index, pred, target)`` triple, each batch's queries are finalised ON THE SPOT through
the same grouped kernel and folded into O(1) mergeable scalars (value sum/count/min/max,
all sum/min/max-reduced) plus a count-min sketch over query ids
(``torchmetrics_tpu.sketch.countmin``) that DETECTS the one approximation this makes: a
query whose documents straddle an update-batch boundary is scored per fragment instead of
once whole. ``straddled_queries`` reports the (never-under-) estimate, and compute warns
when it is nonzero. With batch-aligned queries — the common evaluation layout — sketch
mode is exact. State is ~16 KB regardless of corpus size, and every robustness seam
(snapshot/journal/quorum sync) ships the fixed blob instead of the stream.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

from torchmetrics_tpu.functional.retrieval import _flat
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.sketch.countmin import cm_query, cm_update
from torchmetrics_tpu.sketch.state import countmin_spec, register_sketch_state
from torchmetrics_tpu.utils.checks import _check_retrieval_inputs
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError, TorchMetricsUserWarning
from torchmetrics_tpu.utils.prints import rank_zero_warn


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


@jax.jit
def _group_stats(indexes: Array):
    """(num distinct queries, longest query length) — device-side, O(N log N)."""
    idx_s = jnp.sort(indexes)
    is_new, _gid, start = _flat.dense_groups(idx_s)
    within = jnp.arange(idx_s.shape[0]) - start
    return jnp.sum(is_new), jnp.max(within) + 1


@jax.jit
def _max_valid_per_query(indexes: Array, valid: Array) -> Array:
    """Longest count of VALID (non-ignored) docs in any query — device-side."""
    order = jnp.argsort(indexes, stable=True)
    _is_new, gid, _start = _flat.dense_groups(indexes[order])
    counts = jax.ops.segment_sum(valid[order], gid, num_segments=indexes.shape[0])
    return jnp.max(counts)


@functools.partial(jax.jit, static_argnames=("q_pad", "l_max"))
def _build_rectangles(indexes: Array, preds: Array, target: Array, valid: Array, q_pad: int, l_max: int):
    """Scatter the flat (N,) streams into padded (q_pad, l_max) query rectangles, on device.

    Group ids come from a stable sort over ``indexes`` (dense rank), within-group positions
    from a cummax over group starts — no host round-trip, no dynamic shapes.
    """
    order = jnp.argsort(indexes, stable=True)
    idx_s = indexes[order]
    _is_new, gid, start = _flat.dense_groups(idx_s)
    within = jnp.arange(idx_s.shape[0]) - start
    flat = gid * l_max + within

    def scat(v: Array) -> Array:
        return jnp.zeros((q_pad * l_max,), jnp.float32).at[flat].set(v).reshape(q_pad, l_max)

    v_s = valid[order].astype(jnp.float32)
    return scat(preds[order].astype(jnp.float32)), scat(target[order].astype(jnp.float32) * v_s), scat(v_s)


def _retrieval_aggregate(values: Array, aggregation="mean") -> Array:
    """mean/median/min/max or callable (reference ``base.py:25-40``)."""
    if aggregation == "mean":
        return jnp.mean(values) if values.size else jnp.zeros(())
    if aggregation == "median":
        return jnp.median(values)
    if aggregation == "min":
        return jnp.min(values)
    if aggregation == "max":
        return jnp.max(values)
    return aggregation(values)


def _masked_aggregate(values: Array, include: Array, aggregation: str) -> Array:
    """Trace-safe twin of ``_retrieval_aggregate`` over an inclusion mask (0 when none included)."""
    inc = include.astype(jnp.float32)
    m = jnp.sum(inc)
    if aggregation == "mean":
        return jnp.where(m > 0, jnp.sum(values * inc) / jnp.maximum(m, 1.0), 0.0)
    if aggregation == "min":
        return jnp.where(m > 0, jnp.min(jnp.where(include, values, jnp.inf)), 0.0)
    if aggregation == "max":
        return jnp.where(m > 0, jnp.max(jnp.where(include, values, -jnp.inf)), 0.0)
    if aggregation == "median":
        v = jnp.sort(jnp.where(include, values, jnp.inf))
        lo = jnp.maximum(jnp.floor((m - 1) / 2), 0).astype(jnp.int32)
        hi = jnp.maximum(jnp.ceil((m - 1) / 2), 0).astype(jnp.int32)
        return jnp.where(m > 0, (v[lo] + v[hi]) / 2.0, 0.0)
    raise ValueError(f"Unsupported fused aggregation: {aggregation!r}")


class RetrievalMetric(Metric):
    """Base for retrieval metrics (reference ``base.py:43``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    allow_non_binary_target = False
    #: which per-query count defines an "empty" query for the sketch path ("pos"
    #: everywhere except FallOut, which empties on missing NEGATIVES)
    _sketch_empty_from = "pos"

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation="mean",
        approx: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.jit_compute = False  # grouping is data-dependent; the kernel itself is jitted+vmapped
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(
                f"Argument `empty_target_action` received a wrong value `{empty_target_action}`."
            )
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable."
            )
        self.aggregation = aggregation
        if approx not in (None, "sketch"):
            raise ValueError(f"Argument `approx` must be None or 'sketch', got {approx!r}")
        self.approx = approx
        if approx == "sketch":
            if type(self)._metric_kernel is RetrievalMetric._metric_kernel:
                raise TorchMetricsUserError(
                    f"{type(self).__name__} does not support approx='sketch' (no per-query"
                    " kernel to finalise batches with)."
                )
            if callable(aggregation) or aggregation == "median":
                raise TorchMetricsUserError(
                    "approx='sketch' keeps O(1) mergeable aggregates, which exist for"
                    " aggregation='mean'/'min'/'max' — median and custom callables need"
                    " the exact (cat-state) mode."
                )
            # per-batch grouped finalisation is data-dependent (host-shaped rectangles),
            # so the sketch update runs eagerly and cannot fold under lax.scan
            self.jit_update = False
            self.scan_update = False
            self.add_state("value_sum", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
            self.add_state("query_count", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
            self.add_state("value_min", jnp.asarray(jnp.inf, jnp.float32), dist_reduce_fx="min")
            self.add_state("value_max", jnp.asarray(-jnp.inf, jnp.float32), dist_reduce_fx="max")
            self.add_state("straddled", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
            register_sketch_state(self, "query_cms", countmin_spec())
        else:
            self.add_state("indexes", [], dist_reduce_fx=None)
            self.add_state("preds", [], dist_reduce_fx=None)
            self.add_state("target", [], dist_reduce_fx=None)

    def _validate(self, preds, target, indexes=None) -> None:
        if indexes is None or preds is None or target is None:
            raise ValueError("Arguments ``indexes``, ``preds`` and ``target`` cannot be None")

    def _update(self, state, preds, target, indexes=None):
        # reference argument order (base.py:134): update(preds, target, indexes)
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        if self.approx == "sketch":
            return self._sketch_update(state, indexes, preds, target.astype(jnp.float32))
        return {"indexes": indexes, "preds": preds, "target": target.astype(jnp.float32)}

    # ---------------------------------------------------------- streaming sketch mode
    def _sketch_update(self, state, indexes: Array, preds: Array, target: Array):
        """Finalise THIS batch's queries and fold them into the O(1) running aggregates.

        Same kernel, same empty-action semantics as the exact compute — the only
        difference is WHEN queries are scored: here, per batch, instead of once over the
        full concatenated stream. The count-min sketch tallies query ids so fragments of
        a batch-straddling query are detected (``straddled`` is a never-under estimate).
        """
        if self.ignore_index is not None:
            valid = (target != self.ignore_index).astype(jnp.float32)
            target = target * valid
        else:
            valid = jnp.ones(target.shape, jnp.float32)
        values, pos_count, neg_count, valid_count = self._grouped_values(
            indexes, preds, target, valid=valid
        )
        has_valid = valid_count > 0
        empty_axis = pos_count if self._sketch_empty_from == "pos" else neg_count
        empty = (empty_axis == 0) & has_valid
        action = self.empty_target_action
        if action == "error":
            # explicit one-shot D2H read (TPU001), paid only under the "error" action —
            # exactly the exact-mode contract, just at update time instead of compute
            if bool(jax.device_get(jnp.any(empty))):
                raise ValueError(
                    "`update` method was provided with a query with no "
                    + ("positive" if self._sketch_empty_from == "pos" else "negative")
                    + " target."
                )
            include = has_valid
        elif action == "skip":
            include = has_valid & ~empty
        else:
            values = jnp.where(empty, 1.0 if action == "pos" else 0.0, values)
            include = has_valid
        stats = self._sketch_fold(state, indexes, values, include.astype(jnp.float32))
        return stats

    def _sketch_fold(self, state, indexes, values, inc):
        """One jitted fold of per-query values + id stream into the sketch states."""
        fn = self._jit_cache.get("sketch_fold")
        if fn is None:
            def fold(st, indexes, values, inc):
                vsum = jnp.sum(values * inc)
                vcnt = jnp.sum(inc)
                vmin = jnp.min(jnp.where(inc > 0, values, jnp.inf))
                vmax = jnp.max(jnp.where(inc > 0, values, -jnp.inf))
                ids_sorted = jnp.sort(indexes)
                is_new = jnp.concatenate(
                    [jnp.ones((1,), jnp.float32),
                     (ids_sorted[1:] != ids_sorted[:-1]).astype(jnp.float32)]
                )
                seen = (cm_query(st["query_cms"], ids_sorted) > 0).astype(jnp.float32)
                return {
                    "value_sum": st["value_sum"] + vsum,
                    "query_count": st["query_count"] + vcnt,
                    "value_min": jnp.minimum(st["value_min"], vmin),
                    "value_max": jnp.maximum(st["value_max"], vmax),
                    "straddled": st["straddled"] + jnp.sum(is_new * seen),
                    "query_cms": cm_update(st["query_cms"], ids_sorted, weights=is_new),
                }

            fn = jax.jit(fold)
            self._jit_cache["sketch_fold"] = fn
        return fn(
            {k: state[k] for k in ("value_sum", "query_count", "value_min", "value_max",
                                   "straddled", "query_cms")},
            indexes, values, inc,
        )

    @property
    def straddled_queries(self) -> int:
        """Estimated queries whose documents spanned more than one update batch (sketch
        mode only; count-min backed, never an underestimate). Each such query was scored
        per fragment — with batch-aligned queries this is 0 and sketch mode is exact."""
        if self.approx != "sketch":
            return 0
        self._state.guard_readable()
        return int(jax.device_get(self._state.tensors["straddled"]))

    def _sketch_compute(self, state) -> Array:
        cnt = state["query_count"]
        straddled = int(jax.device_get(state["straddled"]))
        if straddled:
            rank_zero_warn(
                f"{type(self).__name__}(approx='sketch'): ~{straddled} query id(s) appeared"
                " in more than one update batch and were scored per fragment. Align query"
                " boundaries with update batches (or use exact mode) for exact values.",
                TorchMetricsUserWarning,
            )
        if self.aggregation == "min":
            value = jnp.where(cnt > 0, state["value_min"], 0.0)
        elif self.aggregation == "max":
            value = jnp.where(cnt > 0, state["value_max"], 0.0)
        else:
            value = jnp.where(cnt > 0, state["value_sum"] / jnp.maximum(cnt, 1.0), 0.0)
        return value

    # ------------------------------------------------------------ grouped kernel
    def _metric_kernel(self, preds: Array, target: Array, mask: Array) -> Array:
        """Single-query masked kernel; subclasses return a scalar."""
        raise NotImplementedError

    def _grouped_values(
        self, indexes: Array, preds: Array, target: Array,
        kernel: Optional[Callable] = None, cache_key: str = "grouped_kernel",
        valid: Optional[Array] = None,
    ):
        """Group queries and run the vmapped kernel, entirely on device.

        Only O(1) group statistics (query count, longest query) and the final per-query (q,)
        vectors ever cross the device→host boundary — the raw (N,) states never transfer back
        (D2H is the dominant cost on tunneled/remote accelerators; was 97% of compute() time).

        Returns device arrays ``(values, pos_count, neg_count, valid_count)``, each ``(q,)``;
        ``valid_count == 0`` marks queries whose docs were all ``ignore_index`` (the reference
        drops those before grouping — callers must exclude them).
        """
        kernel = kernel or self._metric_kernel
        if valid is None:
            valid = jnp.ones(jnp.shape(indexes), jnp.float32)
        q, max_len = (int(x) for x in jax.device_get(_group_stats(indexes)))
        q_pad, l_max = _next_pow2(q), _next_pow2(max_len)
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            def run(indexes, preds, target, valid, q_pad, l_max, q):
                preds_pad, target_pad, mask_pad = _build_rectangles(
                    indexes, preds, target, valid, q_pad, l_max
                )
                values = jax.vmap(kernel)(preds_pad, target_pad, mask_pad)
                valid_count = jnp.sum(mask_pad, axis=1)
                pos_count = jnp.sum(target_pad * mask_pad, axis=1)
                # mask out the q..q_pad padding rows so callers can aggregate on device
                row_real = jnp.arange(q_pad) < q
                valid_count = jnp.where(row_real, valid_count, 0.0)
                return values, pos_count, valid_count - pos_count, valid_count

            fn = jax.jit(run, static_argnames=("q_pad", "l_max", "q"))
            self._jit_cache[cache_key] = fn
        values, pos, neg, cnt = fn(indexes, preds, target, valid, q_pad=q_pad, l_max=l_max, q=q)
        return values[:q], pos[:q], neg[:q], cnt[:q]

    def _grouped_aggregate(
        self, indexes: Array, preds: Array, target: Array, valid: Array,
        empty_from: str, no_target_msg: str,
        kernel: Optional[Callable] = None, cache_key: str = "grouped_agg",
    ) -> Array:
        """Fused compute: rectangle build + kernel + empty-action + aggregation in ONE launch.

        Exactly two device round-trips total (group stats, then this launch) — per-launch sync
        latency is the dominant cost on tunneled/remote accelerators, so everything after the
        shape-determining stats is one program. ``empty_from`` ∈ {"pos", "neg"} picks which
        count defines an "empty" query (FallOut uses negatives, reference ``fall_out.py:126``).
        Falls back to the unfused path for callable aggregations.
        """
        kernel = kernel or self._metric_kernel
        q, max_len = (int(x) for x in jax.device_get(_group_stats(indexes)))
        q_pad, l_max = _next_pow2(q), _next_pow2(max_len)
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            action = self.empty_target_action
            aggregation = self.aggregation

            def run(indexes, preds, target, valid, q_pad, l_max, q):
                preds_pad, target_pad, mask_pad = _build_rectangles(
                    indexes, preds, target, valid, q_pad, l_max
                )
                values = jax.vmap(kernel)(preds_pad, target_pad, mask_pad)
                valid_count = jnp.sum(mask_pad, axis=1)
                pos_count = jnp.sum(target_pad * mask_pad, axis=1)
                neg_count = valid_count - pos_count
                row_real = jnp.arange(q_pad) < q
                has_valid = row_real & (valid_count > 0)
                empty = (pos_count == 0 if empty_from == "pos" else neg_count == 0) & has_valid
                any_empty = jnp.any(empty)
                if action == "skip":
                    include = has_valid & ~empty
                else:
                    values = jnp.where(empty, 1.0 if action == "pos" else 0.0, values)
                    include = has_valid
                result = _masked_aggregate(values, include, aggregation)
                return result, any_empty

            fn = jax.jit(run, static_argnames=("q_pad", "l_max", "q"))
            self._jit_cache[cache_key] = fn
        result, any_empty = fn(indexes, preds, target, valid, q_pad=q_pad, l_max=l_max, q=q)
        if self.empty_target_action == "error" and bool(jax.device_get(any_empty)):
            # explicit one-shot D2H read (TPU001): only the "error" action needs this flag on
            # host; the other actions impute inside the fused kernel and never block here
            raise ValueError(no_target_msg)
        return result

    # ------------------------------------------------------------ flat (segment-reduce) path
    def _flat_values(self, ctx):
        """Per-query values over the flat sorted-doc context (``functional/retrieval/_flat.py``)
        or ``None`` to fall back to the rectangle path. Subclasses override."""
        return None

    @staticmethod
    def _pad_flat(indexes: Array, preds: Array, target: Array, valid: Array):
        """Pad the flat doc streams to a power of two so recompiles stay bounded. Filler docs
        carry the maximal query id (they sort last, forming empty segments) and ``valid=0``."""
        n = int(indexes.shape[0])
        n_pad = _next_pow2(n)
        if n_pad == n:
            return indexes, preds, target, valid
        pad = n_pad - n
        return (
            jnp.concatenate([indexes, jnp.full((pad,), jnp.iinfo(indexes.dtype).max, indexes.dtype)]),
            jnp.concatenate([preds, jnp.zeros((pad,), preds.dtype)]),
            jnp.concatenate([target, jnp.zeros((pad,), target.dtype)]),
            jnp.concatenate([valid, jnp.zeros((pad,), valid.dtype)]),
        )

    def _flat_aggregate(
        self, indexes: Array, preds: Array, target: Array, valid: Array,
        empty_from: str, no_target_msg: str, cache_key: str = "flat_agg",
    ) -> Array:
        """Fused flat compute: sort + segment kernel + empty-action + aggregation, ONE launch.

        Unlike ``_grouped_aggregate`` there is NO shape-determining host round-trip: every
        shape is static in the (padded) doc count, so nothing blocks until the caller reads
        the result — the whole compute pipelines behind prior work on high-latency links.
        """
        indexes, preds, target, valid = self._pad_flat(indexes, preds, target, valid)
        # CPU backend: the sort permutation is computed eagerly on the host (numpy packed-key
        # argsort, ~10x XLA:CPU's comparator sort) and becomes a plain jit argument; on TPU
        # it is None and the in-graph lax.sort keeps everything on device
        perm = _flat.host_sort_perm(indexes, preds, valid)
        ideal_perm = (
            _flat.host_ideal_perm(indexes, target, valid, perm)
            if getattr(self, "_flat_needs_ideal_perm", False)
            else None
        )
        cache_key = cache_key + ("@perm" if perm is not None else "")
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            action = self.empty_target_action
            aggregation = self.aggregation
            top_k = getattr(self, "top_k", None)

            def run(indexes, preds, target, valid, perm=None, ideal_perm=None):
                ctx = _flat.build_context(
                    indexes, preds, target, valid, top_k, perm=perm, ideal_perm=ideal_perm
                )
                values = self._flat_values(ctx)
                n_valid_seg = ctx["n_valid_seg"]
                pos_seg = ctx["pos_seg"]
                has_valid = n_valid_seg > 0
                empty = (pos_seg == 0 if empty_from == "pos" else (n_valid_seg - pos_seg) == 0) & has_valid
                any_empty = jnp.any(empty)
                if action == "skip":
                    include = has_valid & ~empty
                else:
                    values = jnp.where(empty, 1.0 if action == "pos" else 0.0, values)
                    include = has_valid
                return _masked_aggregate(values, include, aggregation), any_empty

            fn = jax.jit(run)
            self._jit_cache[cache_key] = fn
        if perm is not None:
            extra = (perm,) + ((ideal_perm,) if ideal_perm is not None else ())
            result, any_empty = fn(indexes, preds, target, valid, *extra)
        else:
            result, any_empty = fn(indexes, preds, target, valid)
        if self.empty_target_action == "error" and bool(jax.device_get(any_empty)):
            # explicit one-shot D2H read (TPU001), paid only under the "error" action
            raise ValueError(no_target_msg)
        return result

    def _state_arrays(self, state):
        """Concatenated device arrays (indexes, preds, target, valid-mask) or None when empty."""

        def _cat(val):
            # list state pre-sync; a single already-concatenated array post-sync
            if isinstance(val, (list, tuple)):
                return jnp.concatenate([jnp.atleast_1d(x) for x in val]) if len(val) else None
            return jnp.reshape(val, (-1,))

        indexes = _cat(state["indexes"])
        if indexes is None or indexes.size == 0:
            return None
        preds = _cat(state["preds"])
        target = _cat(state["target"]).astype(jnp.float32)
        if self.ignore_index is not None:
            valid = (target != self.ignore_index).astype(jnp.float32)
            target = target * valid
        else:
            valid = jnp.ones(target.shape, jnp.float32)
        return indexes, preds, target, valid

    def _select_values(self, values, empty, has_valid, no_target_msg: str):
        """Apply empty_target_action + drop fully-ignored queries; small host-side (q,) work."""
        values_np = np.asarray(values)
        empty = np.asarray(empty) & np.asarray(has_valid)
        if self.empty_target_action == "error" and bool(empty.any()):
            raise ValueError(no_target_msg)
        if self.empty_target_action == "skip":
            values_np = values_np[~empty & np.asarray(has_valid)]
        else:
            if self.empty_target_action == "pos":
                values_np = np.where(empty, 1.0, values_np)
            else:  # "neg"
                values_np = np.where(empty, 0.0, values_np)
            values_np = values_np[np.asarray(has_valid)]
        return values_np

    def _compute(self, state):
        if self.approx == "sketch":
            return self._sketch_compute(state)
        arrays = self._state_arrays(state)
        if arrays is None:
            return jnp.zeros(())
        indexes, preds, target, valid = arrays
        msg = "`compute` method was provided with a query with no positive target."
        if callable(self.aggregation):  # custom aggregations run on host (unfused path)
            values, pos_count, _neg, valid_count = self._grouped_values(
                indexes, preds, target, valid=valid
            )
            values_np = self._select_values(values, pos_count == 0, valid_count > 0, msg)
            return _retrieval_aggregate(jnp.asarray(values_np), self.aggregation)
        if type(self)._flat_values is not RetrievalMetric._flat_values:
            return self._flat_aggregate(indexes, preds, target, valid, "pos", msg)
        return self._grouped_aggregate(indexes, preds, target, valid, "pos", msg)
