"""RetrievalMetric base (reference ``src/torchmetrics/retrieval/base.py:43``).

TPU-native compute: instead of the reference's per-query Python loop
(``base.py:165-182``), queries are grouped on the host, padded to a ``(Q, L_max)`` rectangle
(shapes rounded up to powers of two to bound recompiles) and the masked single-query kernel is
vmapped over the batch — one fused device program for all queries.

State: three list states with ``dist_reduce_fx=None`` (gather-without-reduce,
reference ``base.py:130-132``).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.checks import _check_retrieval_inputs
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError


def _next_pow2(x: int) -> int:
    return 1 << max(0, (x - 1)).bit_length()


def _retrieval_aggregate(values: Array, aggregation="mean") -> Array:
    """mean/median/min/max or callable (reference ``base.py:25-40``)."""
    if aggregation == "mean":
        return jnp.mean(values) if values.size else jnp.zeros(())
    if aggregation == "median":
        return jnp.median(values)
    if aggregation == "min":
        return jnp.min(values)
    if aggregation == "max":
        return jnp.max(values)
    return aggregation(values)


class RetrievalMetric(Metric):
    """Base for retrieval metrics (reference ``base.py:43``)."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    allow_non_binary_target = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        aggregation="mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.jit_compute = False  # grouping is data-dependent; the kernel itself is jitted+vmapped
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(
                f"Argument `empty_target_action` received a wrong value `{empty_target_action}`."
            )
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        self.ignore_index = ignore_index
        if not (aggregation in ("mean", "median", "min", "max") or callable(aggregation)):
            raise ValueError(
                "Argument `aggregation` must be one of `mean`, `median`, `min`, `max` or a custom callable."
            )
        self.aggregation = aggregation
        self.add_state("indexes", [], dist_reduce_fx=None)
        self.add_state("preds", [], dist_reduce_fx=None)
        self.add_state("target", [], dist_reduce_fx=None)

    def _validate(self, preds, target, indexes=None) -> None:
        if indexes is None or preds is None or target is None:
            raise ValueError("Arguments ``indexes``, ``preds`` and ``target`` cannot be None")

    def _update(self, state, preds, target, indexes=None):
        # reference argument order (base.py:134): update(preds, target, indexes)
        indexes, preds, target = _check_retrieval_inputs(
            indexes, preds, target, allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        return {"indexes": indexes, "preds": preds, "target": target.astype(jnp.float32)}

    # ------------------------------------------------------------ grouped kernel
    def _metric_kernel(self, preds: Array, target: Array, mask: Array) -> Array:
        """Single-query masked kernel; subclasses return a scalar."""
        raise NotImplementedError

    def _grouped_values(
        self, indexes: np.ndarray, preds: np.ndarray, target: np.ndarray,
        kernel: Optional[Callable] = None, cache_key: str = "grouped_kernel",
    ):
        """Pad queries to a rectangle and run the vmapped kernel once."""
        kernel = kernel or self._metric_kernel
        uniq, inv, counts = np.unique(indexes, return_inverse=True, return_counts=True)
        q = len(uniq)
        l_max = _next_pow2(int(counts.max()))
        q_pad = _next_pow2(q)
        order = np.argsort(inv, kind="stable")
        # position of each element within its query group
        offsets = np.zeros(q + 1, np.int64)
        np.cumsum(counts, out=offsets[1:])
        within = np.arange(len(indexes)) - offsets[inv[order]]
        preds_pad = np.zeros((q_pad, l_max), np.float32)
        target_pad = np.zeros((q_pad, l_max), np.float32)
        mask_pad = np.zeros((q_pad, l_max), np.float32)
        rows = inv[order]
        preds_pad[rows, within] = preds[order]
        target_pad[rows, within] = target[order]
        mask_pad[rows, within] = 1.0
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            fn = jax.jit(jax.vmap(kernel))
            self._jit_cache[cache_key] = fn
        values = fn(jnp.asarray(preds_pad), jnp.asarray(target_pad), jnp.asarray(mask_pad))
        return values[:q], target_pad[:q], mask_pad[:q]

    def _compute(self, state):
        indexes = np.asarray(state["indexes"])
        preds = np.asarray(state["preds"])
        target = np.asarray(state["target"])
        if self.ignore_index is not None:
            keep = target != self.ignore_index
            indexes, preds, target = indexes[keep], preds[keep], target[keep]
        if indexes.size == 0:
            return jnp.zeros(())
        values, target_pad, mask_pad = self._grouped_values(indexes, preds, target)
        empty = (target_pad * mask_pad).sum(axis=1) == 0
        if self.empty_target_action == "error" and bool(empty.any()):
            raise ValueError("`compute` method was provided with a query with no positive target.")
        values_np = np.asarray(values)
        if self.empty_target_action == "skip":
            values_np = values_np[~empty]
        elif self.empty_target_action == "pos":
            values_np = np.where(empty, 1.0, values_np)
        else:  # "neg"
            values_np = np.where(empty, 0.0, values_np)
        return _retrieval_aggregate(jnp.asarray(values_np), self.aggregation)
