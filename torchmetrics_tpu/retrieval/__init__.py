from torchmetrics_tpu.retrieval.base import RetrievalMetric
from torchmetrics_tpu.retrieval.metrics import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)

__all__ = [
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalMetric",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
]
