"""Non-blocking serving tier: ``update_async`` with bounded backpressure (docs/serving.md).

Opt-in per metric: ``metric.serve(ServeOptions(...), journal=...)`` configures the
engine, ``metric.update_async(*batch)`` enqueues and returns an :class:`IngestTicket`.
The disabled path costs one attribute check per update. See ``docs/serving.md`` for the
window state machine, the on-full semantics table, the enqueue-time WAL contract, the
adaptive "Control loop" (``metric.serve(control=ServeController())``), and the quiesce
rules; ``docs/robustness.md`` for the chaos coverage.
"""
from torchmetrics_tpu.serve.control import (
    ControlOptions,
    DriftSnapshotter,
    ServeController,
    SharedDrain,
    adaptive_recover,
    control_options_from_env,
    shed_seqs,
)
from torchmetrics_tpu.serve.engine import DrainKilled, IngestEngine, IngestTicket
from torchmetrics_tpu.serve.options import (
    ENV_SERVE_MAX_INFLIGHT,
    ENV_SERVE_ON_FULL,
    ENV_SERVE_QUEUE_TIMEOUT,
    ENV_SERVE_STAGING_SLOTS,
    ServeOptions,
    serve_options_from_env,
)
from torchmetrics_tpu.serve.staging import StagingPipeline

__all__ = [
    "ControlOptions",
    "DrainKilled",
    "DriftSnapshotter",
    "IngestEngine",
    "IngestTicket",
    "ServeController",
    "ServeOptions",
    "SharedDrain",
    "StagingPipeline",
    "adaptive_recover",
    "control_options_from_env",
    "serve_options_from_env",
    "shed_seqs",
    "ENV_SERVE_MAX_INFLIGHT",
    "ENV_SERVE_ON_FULL",
    "ENV_SERVE_QUEUE_TIMEOUT",
    "ENV_SERVE_STAGING_SLOTS",
]
