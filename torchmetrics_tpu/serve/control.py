"""The actuator tier: a deterministic control loop over the serving signal stack.

PR 11 built the engine, PR 12 the signals, PR 13 the drift detectors, PR 15 the memory
ledger — all of it inert: ``ServeOptions`` is a static config, so an overload today ends
in sheds and a post-mortem bundle instead of adaptation. :class:`ServeController`
closes the loop from signals to actions (docs/serving.md "Control loop"):

- **Adaptive coalesce/linger** — the micro-batching dwell (``linger_ms``) and the
  coalesce width track queue occupancy: a backed-up queue with a healthy latency
  budget raises the dwell (wider scan launches), a rising p99 burn (occupancy at the
  saturation band — Little's law makes window occupancy the deterministic
  enqueue→commit latency proxy) collapses it so commits launch immediately.
- **Escalating admission** — a ``block`` engine graduates block → timed-block → shed
  as the multi-window occupancy burn crosses the escalation band, and de-escalates
  symmetrically on recovery. Each rung is a park budget: ``block`` parks up to
  ``queue_timeout_s``, ``timed`` up to ``timed_block_timeout_s``, ``shed`` not at
  all — and with a controller attached, an exhausted park budget *sheds* (a journaled
  decision) instead of raising, so degradation is graceful end to end.
- **Shared drain** — :class:`SharedDrain` runs ONE drain thread across many engines,
  scheduled by weighted deficit round-robin on per-engine SLO burn (occupancy + shed
  burn): a hot tenant earns proportionally more quanta but every engine keeps the
  base quantum, so it cannot starve the fleet of engines in one process.
- **Drift-triggered auto-snapshot** — :class:`DriftSnapshotter` keeps a rolling
  pre-shift snapshot while the detectors are quiet; the evaluation that fires an
  alarm lands the pre-shift blob + an at-alarm blob + a post-mortem bundle, so every
  detected shift has a checkpoint to diff against.

**Determinism contract.** The decision path reads only update-count/queue-state
derived signals — the tick counter is the offered-batch count, the burn windows are
tick-indexed rings of window occupancy — never the wall clock (TPU017: a clocked
decision is irreproducible under replay). Hysteresis bands plus a per-actuator
decision-rate cap (``min_hold_ticks``) bound actuator toggles to at most one per
actuator per ``min_hold_ticks`` offered batches, so oscillating load cannot thrash.
Every transition and every controller shed is (1) a flight-recorder event carrying
the triggering signal values and (2) a record in the **decision journal** — a
:class:`~torchmetrics_tpu.robust.journal.Journal` beside the WAL (``<wal>-control``).
Replay of an adaptive run is bit-identical: :func:`adaptive_recover` replays the WAL
skipping exactly the journaled shed sequence numbers, which is the whole effect the
controller had on *values* (dwell/coalesce changes alter launch shape only — the scan
tier's bit-identity contract covers those).
"""
from __future__ import annotations

import os
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, FrozenSet, List, Optional, Tuple

from torchmetrics_tpu.obs import bundle as _bundle
from torchmetrics_tpu.obs import flightrec as _flightrec
from torchmetrics_tpu.obs import telemetry
from torchmetrics_tpu.obs import trace as _trace
from torchmetrics_tpu.serve.options import ServeOptions, _env_num
from torchmetrics_tpu.utils.exceptions import ServeError

ENV_CONTROL_DECISION_EVERY = "TM_TPU_SERVE_CONTROL_DECISION_EVERY"
ENV_CONTROL_MIN_HOLD = "TM_TPU_SERVE_CONTROL_MIN_HOLD_TICKS"
ENV_CONTROL_WINDOW_SHORT = "TM_TPU_SERVE_CONTROL_WINDOW_SHORT"
ENV_CONTROL_WINDOW_LONG = "TM_TPU_SERVE_CONTROL_WINDOW_LONG"
ENV_CONTROL_TIMED_TIMEOUT = "TM_TPU_SERVE_CONTROL_TIMED_TIMEOUT_S"
ENV_CONTROL_LINGER_MAX = "TM_TPU_SERVE_CONTROL_LINGER_MAX_MS"

#: the admission ladder, least → most degraded; the index is the escalation level
MODES: Tuple[str, ...] = ("block", "timed", "shed")

#: control-journal directory suffix beside the engine's WAL directory
CONTROL_DIR_SUFFIX = "-control"


@dataclass(frozen=True)
class ControlOptions:
    """Policy for one :class:`ServeController` (docs/serving.md "Control loop").

    All cadences and windows are in *offered-batch ticks*, never seconds — the
    controller's clock is the update count (TPU017). ``min_hold_ticks`` is the
    per-actuator decision-rate cap: once an actuator changed, it holds for at least
    this many offered batches regardless of what the signals do, which is what makes
    square-wave load thrash-free. The occupancy bands are hysteresis pairs —
    escalation needs the *short and long* window averages above the high band,
    de-escalation needs both below the low band.
    """

    #: run the decision function every this-many offered batches
    decision_every: int = 8
    #: short / long burn windows (offered-batch ticks) for the multi-window burn test
    window_short: int = 16
    window_long: int = 64
    #: per-actuator decision-rate cap: minimum offered-batch ticks between changes
    min_hold_ticks: int = 32
    #: admission ladder hysteresis band (mean window occupancy, 0..1)
    escalate_occupancy: float = 0.85
    deescalate_occupancy: float = 0.35
    #: dwell hysteresis band: raise dwell above the high edge (queue backing up,
    #: latency budget healthy), lower it below the low edge; occupancy at the
    #: escalation band collapses the dwell outright (the p99-burn proxy)
    dwell_raise_occupancy: float = 0.40
    dwell_lower_occupancy: float = 0.15
    #: dwell actuation range/step; coalesce moves by powers of two down to the floor
    linger_max_ms: float = 2.0
    linger_step_ms: float = 0.5
    coalesce_min: int = 1
    #: park budget of the middle admission rung (behavioural, not decisional: the
    #: decision to *be* in timed mode came from tick-derived burn, never the clock)
    timed_block_timeout_s: float = 0.05

    def __post_init__(self) -> None:
        if int(self.decision_every) < 1:
            raise ServeError(f"ControlOptions(decision_every) needs >= 1, got {self.decision_every}")
        if int(self.window_short) < 1:
            raise ServeError(f"ControlOptions(window_short) needs >= 1, got {self.window_short}")
        if int(self.window_long) < int(self.window_short):
            raise ServeError(
                f"ControlOptions(window_long) needs >= window_short, got {self.window_long}"
            )
        if int(self.min_hold_ticks) < 1:
            raise ServeError(f"ControlOptions(min_hold_ticks) needs >= 1, got {self.min_hold_ticks}")
        if not (0.0 < self.deescalate_occupancy < self.escalate_occupancy <= 1.0):
            raise ServeError(
                "ControlOptions needs 0 < deescalate_occupancy < escalate_occupancy <= 1,"
                f" got ({self.deescalate_occupancy}, {self.escalate_occupancy})"
            )
        if not (0.0 <= self.dwell_lower_occupancy < self.dwell_raise_occupancy <= 1.0):
            raise ServeError(
                "ControlOptions needs 0 <= dwell_lower_occupancy < dwell_raise_occupancy <= 1,"
                f" got ({self.dwell_lower_occupancy}, {self.dwell_raise_occupancy})"
            )
        if float(self.linger_max_ms) < 0 or float(self.linger_step_ms) <= 0:
            raise ServeError(
                f"ControlOptions(linger_max_ms/linger_step_ms) need >= 0 / > 0, got"
                f" ({self.linger_max_ms}, {self.linger_step_ms})"
            )
        if int(self.coalesce_min) < 1:
            raise ServeError(f"ControlOptions(coalesce_min) needs >= 1, got {self.coalesce_min}")
        if float(self.timed_block_timeout_s) < 0:
            raise ServeError(
                f"ControlOptions(timed_block_timeout_s) needs >= 0, got {self.timed_block_timeout_s}"
            )


def control_options_from_env() -> ControlOptions:
    """Build :class:`ControlOptions` from the ``TM_TPU_SERVE_CONTROL_*`` env knobs.

    Malformed values degrade to the defaults with a one-shot rank-zero warning, same
    contract as :func:`~torchmetrics_tpu.serve.options.serve_options_from_env`.
    """
    return ControlOptions(
        decision_every=_env_num(ENV_CONTROL_DECISION_EVERY, 8, int, lambda v: v >= 1),
        window_short=_env_num(ENV_CONTROL_WINDOW_SHORT, 16, int, lambda v: v >= 1),
        window_long=_env_num(ENV_CONTROL_WINDOW_LONG, 64, int, lambda v: v >= 1),
        min_hold_ticks=_env_num(ENV_CONTROL_MIN_HOLD, 32, int, lambda v: v >= 1),
        timed_block_timeout_s=_env_num(ENV_CONTROL_TIMED_TIMEOUT, 0.05, float, lambda v: v >= 0),
        linger_max_ms=_env_num(ENV_CONTROL_LINGER_MAX, 2.0, float, lambda v: v >= 0),
    )


class _Channel:
    """Per-engine actuator + signal state (controller-private, guarded by the
    controller lock). The actuator fields (``mode_idx``/``linger_ms``/``coalesce``)
    are only ever written by :meth:`ServeController._transition` — the single seam
    that also lands the flight event and the decision-journal record (TPU024)."""

    def __init__(self, engine: Any, opts: ControlOptions) -> None:
        self.engine = engine
        base: ServeOptions = engine.options
        self.mode_idx = 0
        self.linger_ms = float(base.linger_ms)
        self.coalesce = int(base.coalesce)
        self.tick = 0
        #: one occupancy sample per offered batch — the tick-indexed burn window
        self.occ_ring: Deque[float] = deque(maxlen=int(opts.window_long))
        self.shed_ring: Deque[int] = deque(maxlen=int(opts.window_long))
        self.last_change: Dict[str, int] = {"admission": -(10**9), "dwell": -(10**9)}
        self.transitions: Dict[str, int] = {"admission": 0, "dwell": 0}
        self.journal: Optional[Any] = None

    def occupancy(self, window: int) -> float:
        if not self.occ_ring:
            return 0.0
        n = min(window, len(self.occ_ring))
        tail = list(self.occ_ring)[-n:]
        return sum(tail) / n

    def shed_burn(self, window: int) -> float:
        if not self.shed_ring:
            return 0.0
        n = min(window, len(self.shed_ring))
        tail = list(self.shed_ring)[-n:]
        return sum(tail) / n


class ServeController:
    """Deterministic signals→actions loop for one or more :class:`IngestEngine` s.

    Attach with :meth:`attach` (or ``metric.serve(control=...)``). The engine calls
    :meth:`note_offered` once per offered batch under its own condition lock; every
    ``decision_every`` ticks the controller evaluates the tick-windowed occupancy
    burn and moves the actuators through :meth:`_transition` — the only mutation
    seam, which journals the decision and lands the flight event with the triggering
    signal values. All engine-facing reads (:meth:`linger_ms` / :meth:`coalesce` /
    :meth:`admission`) are plain attribute loads — nothing on the drain hot path
    blocks on the controller lock.
    """

    def __init__(self, options: Optional[ControlOptions] = None) -> None:
        self.options = options or ControlOptions()
        self._lock = threading.Lock()
        self._channels: Dict[int, _Channel] = {}
        self._stats = {
            "ticks": 0, "decisions": 0, "escalations": 0, "deescalations": 0,
            "dwell_changes": 0, "sheds": 0,
        }
        #: in-memory decision log (the durable twin rides the control journal)
        self.decisions: List[Dict[str, Any]] = []

    # ------------------------------------------------------------------ attachment
    def attach(self, engine: Any) -> Any:
        """Bind this controller to ``engine``; returns the engine.

        When the engine carries a write-ahead journal, the decision journal opens
        beside it (``<wal>-control``) so replay can subtract the journaled sheds.
        """
        with self._lock:
            ch = self._channels.get(id(engine))
            if ch is None:
                ch = _Channel(engine, self.options)
                if getattr(engine.journal, "path", None):
                    from torchmetrics_tpu.robust.journal import Journal

                    ch.journal = Journal(os.fspath(engine.journal.path) + CONTROL_DIR_SUFFIX)
                self._channels[id(engine)] = ch
        engine.attach_controller(self)
        _flightrec.record(
            "control.attach", engines=len(self._channels),
            journaled=ch.journal is not None,
        )
        return engine

    def _channel(self, engine: Any) -> _Channel:
        ch = self._channels.get(id(engine))
        if ch is None:
            raise ServeError("This engine is not attached to the controller; call attach() first")
        return ch

    # ----------------------------------------------------- engine-facing actuators
    def linger_ms(self, engine: Any) -> float:
        """Live micro-batching dwell for ``engine`` (read by the drain each window)."""
        return self._channel(engine).linger_ms

    def coalesce(self, engine: Any) -> int:
        """Live coalesce width for ``engine`` (read by the drain each window)."""
        return self._channel(engine).coalesce

    def admission(self, engine: Any) -> Tuple[str, float]:
        """Effective admission rung for a full window: ``(mode, park_budget_s)``."""
        ch = self._channel(engine)
        mode = MODES[ch.mode_idx]
        if mode == "block":
            return mode, float(engine.options.queue_timeout_s)
        if mode == "timed":
            return mode, float(self.options.timed_block_timeout_s)
        return mode, 0.0

    def shed_burn(self, engine: Any) -> float:
        """Short-window shed fraction — the :class:`SharedDrain` weight component."""
        return self._channel(engine).shed_burn(self.options.window_short)

    # --------------------------------------------------------------- signal intake
    def note_offered(self, engine: Any, depth: int, shed: bool = False,
                     wal_seq: Optional[int] = None) -> None:
        """One offered batch: sample queue state, journal a shed, maybe decide.

        Called by the engine under its own condition lock, once per ``enqueue`` —
        the tick counter this advances IS the controller's clock (update-count
        derived, never wall time). ``depth`` is the window depth the offer observed;
        ``wal_seq`` is the batch's write-ahead journal sequence number, recorded on a
        shed so :func:`adaptive_recover` can skip exactly the dropped records.
        """
        opts = self.options
        with self._lock:
            ch = self._channel(engine)
            ch.tick += 1
            self._stats["ticks"] += 1
            ch.occ_ring.append(min(1.0, depth / float(engine.options.max_inflight)))
            ch.shed_ring.append(1 if shed else 0)
            if shed:
                self._stats["sheds"] += 1
                self._note_shed_locked(ch, wal_seq)
            if ch.tick % opts.decision_every == 0:
                self._decide_locked(ch)

    def note_committed(self, engine: Any, n: int) -> None:
        """Drain-side commit notification (kept for scheduling weight freshness)."""
        with self._lock:
            ch = self._channels.get(id(engine))
            if ch is not None and ch.occ_ring:
                # commits relieve pressure between offers; reflect the drained depth
                # so a quiet stream's next decision sees the recovery, not the burst
                depth = len(engine._queue) + engine._applying_n
                ch.occ_ring[-1] = min(1.0, depth / float(engine.options.max_inflight))

    def _note_shed_locked(self, ch: _Channel, wal_seq: Optional[int]) -> None:
        mode = MODES[ch.mode_idx]
        _flightrec.record("control.shed", seq=wal_seq, mode=mode, tick=ch.tick)
        if ch.journal is not None and wal_seq is not None:
            ch.journal.append(("shed", {"seq": int(wal_seq), "mode": mode, "tick": ch.tick}))

    # -------------------------------------------------------------- decision core
    def _decide_locked(self, ch: _Channel) -> None:
        opts = self.options
        self._stats["decisions"] += 1
        occ_s = ch.occupancy(opts.window_short)
        occ_l = ch.occupancy(opts.window_long)
        self._decide_admission_locked(ch, occ_s, occ_l)
        self._decide_dwell_locked(ch, occ_s, occ_l)

    def _held(self, ch: _Channel, actuator: str) -> bool:
        return ch.tick - ch.last_change[actuator] < self.options.min_hold_ticks

    def _decide_admission_locked(self, ch: _Channel, occ_s: float, occ_l: float) -> None:
        if ch.engine.options.on_full != "block":
            return  # the ladder only governs engines whose base contract is block
        opts = self.options
        if self._held(ch, "admission"):
            return
        # multi-window burn: escalate only when the pressure is sustained (long
        # window) AND still happening (short window); de-escalate symmetrically
        if ch.mode_idx < len(MODES) - 1 and occ_s >= opts.escalate_occupancy \
                and occ_l >= opts.escalate_occupancy:
            self._transition(ch, "admission", ch.mode_idx + 1, occ_s, occ_l)
        elif ch.mode_idx > 0 and occ_s <= opts.deescalate_occupancy \
                and occ_l <= opts.deescalate_occupancy:
            self._transition(ch, "admission", ch.mode_idx - 1, occ_s, occ_l)

    def _decide_dwell_locked(self, ch: _Channel, occ_s: float, occ_l: float) -> None:
        opts = self.options
        if self._held(ch, "dwell"):
            return
        base: ServeOptions = ch.engine.options
        linger, coalesce = ch.linger_ms, ch.coalesce
        if occ_s >= opts.escalate_occupancy:
            # p99 burn rising (saturation band): collapse the dwell — a deep queue
            # coalesces without lingering, and every extra dwell-ms is pure latency
            linger, coalesce = 0.0, int(base.coalesce)
        elif occ_s >= opts.dwell_raise_occupancy:
            # queue backing up, latency budget healthy: raise the dwell
            linger = min(opts.linger_max_ms, ch.linger_ms + opts.linger_step_ms)
            coalesce = min(int(base.coalesce), max(1, ch.coalesce) * 2)
        elif occ_s <= opts.dwell_lower_occupancy:
            linger = max(0.0, ch.linger_ms - opts.linger_step_ms)
            coalesce = max(int(opts.coalesce_min), ch.coalesce // 2)
        if (linger, coalesce) != (ch.linger_ms, ch.coalesce):
            self._transition(ch, "dwell", (linger, coalesce), occ_s, occ_l)

    def _transition(self, ch: _Channel, actuator: str, to: Any,
                    occ_s: float, occ_l: float) -> None:
        """THE actuator mutation seam: move state + flight event + decision journal.

        Every escalate/de-escalate/dwell change funnels through here so the flight
        recorder and the decision journal see each transition with the triggering
        signal values (jaxlint TPU024 pins this structurally).
        """
        if actuator == "admission":
            frm, ch.mode_idx = MODES[ch.mode_idx], int(to)
            to_name = MODES[ch.mode_idx]
            escalated = MODES.index(to_name) > MODES.index(frm)
            self._stats["escalations" if escalated else "deescalations"] += 1
            kind = "control.escalation" if escalated else "control.deescalation"
        else:
            frm = (ch.linger_ms, ch.coalesce)
            ch.linger_ms, ch.coalesce = float(to[0]), int(to[1])
            to_name = (ch.linger_ms, ch.coalesce)
            self._stats["dwell_changes"] += 1
            kind = "control.decision"
        ch.last_change[actuator] = ch.tick
        ch.transitions[actuator] += 1
        decision = {
            "kind": kind, "actuator": actuator, "from": frm, "to": to_name,
            "tick": ch.tick, "occupancy_short": round(occ_s, 4),
            "occupancy_long": round(occ_l, 4),
        }
        self.decisions.append(decision)
        telemetry.counter("control.decisions").inc()
        _flightrec.record(
            kind, actuator=actuator, frm=str(frm), to=str(to_name), tick=ch.tick,
            occupancy_short=round(occ_s, 4), occupancy_long=round(occ_l, 4),
        )
        if ch.journal is not None:
            ch.journal.append(("decision", decision))

    # -------------------------------------------------------------------- reports
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def channel_report(self, engine: Any) -> Dict[str, Any]:
        """Live actuator positions + toggle counts for one engine."""
        with self._lock:
            ch = self._channel(engine)
            return {
                "mode": MODES[ch.mode_idx], "linger_ms": ch.linger_ms,
                "coalesce": ch.coalesce, "tick": ch.tick,
                "transitions": dict(ch.transitions),
                "occupancy_short": ch.occupancy(self.options.window_short),
                "occupancy_long": ch.occupancy(self.options.window_long),
            }

    def toggle_rate_ok(self, engine: Any) -> bool:
        """The decision-rate-cap invariant the stability suite pins: no actuator may
        have toggled more than once per ``min_hold_ticks`` offered batches."""
        with self._lock:
            ch = self._channel(engine)
            cap = ch.tick / max(1, self.options.min_hold_ticks) + 1
            return all(t <= cap for t in ch.transitions.values())


# ---------------------------------------------------------------------------
# adaptive replay: WAL minus the journaled sheds
# ---------------------------------------------------------------------------

def shed_seqs(control_dir: Any) -> FrozenSet[int]:
    """The WAL sequence numbers the decision journal records as shed."""
    from torchmetrics_tpu.robust.journal import Journal

    jr = control_dir if hasattr(control_dir, "read") else Journal(control_dir)
    out = set()
    for _seq, args, _kwargs in jr.read():
        if args and args[0] == "shed":
            out.add(int(args[1]["seq"]))
    return frozenset(out)


def adaptive_recover(metric: Any, wal_dir: Any, control_dir: Optional[Any] = None,
                     cursor: Any = None) -> Dict[str, Any]:
    """``snapshot + replay(WAL − journaled sheds)``: bit-identical adaptive recovery.

    The controller's only effect on *values* is which offered batches shed (dwell and
    coalesce changes alter launch shape, which the scan tier's bit-identity contract
    already covers), and every shed is a decision-journal record — so replaying the
    write-ahead journal while skipping exactly those sequence numbers reconstructs
    the live adaptive state byte for byte. ``cursor`` passes through to
    :func:`~torchmetrics_tpu.robust.journal.recover` (post-mortem bundle replay).
    """
    from torchmetrics_tpu.robust import journal as _journal

    wal_dir = os.fspath(wal_dir)
    if control_dir is None:
        control_dir = wal_dir + CONTROL_DIR_SUFFIX
    skips = shed_seqs(control_dir) if os.path.isdir(os.fspath(control_dir)) else frozenset()
    out = _journal.recover(metric, wal_dir, cursor=cursor, skip_seqs=skips)
    out["shed_skipped"] = len(skips)
    return out


# ---------------------------------------------------------------------------
# shared drain: one thread, many engines, weighted deficit round-robin
# ---------------------------------------------------------------------------

class SharedDrain:
    """One drain thread serving many engines, scheduled by per-engine SLO burn.

    Weighted deficit round-robin: each scheduling round banks ``quantum × weight``
    credit per engine (weight = 1 + window occupancy + short-window shed burn — the
    per-engine burn proxy), and an engine spends one credit per applied window.
    A hot tenant earns proportionally more service, but every attached engine keeps
    the base quantum and banked credit is capped, so no engine starves. The thread
    participates in the same death/restart latch as per-engine drains: a dead shared
    drain is revived by the next ``ensure_alive`` (any quiesce/enqueue) with a
    flight-recorder event.
    """

    def __init__(self, quantum: float = 1.0, deficit_cap: float = 4.0,
                 name: str = "tm-tpu-shared-drain") -> None:
        self.quantum = float(quantum)
        self.deficit_cap = float(deficit_cap)
        self.name = name
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._engines: List[Any] = []
        # shared-thread-only scratch: the loop is the sole reader AND writer
        self._deficit: Dict[int, float] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self.restarts = 0

    def attach(self, engine: Any) -> Any:
        """Adopt ``engine``: its own drain thread never starts; this one serves it."""
        with self._lock:
            if engine not in self._engines:
                self._engines.append(engine)
            engine._drain_owner = self
            n = len(self._engines)
        _flightrec.record("control.shared_drain_attach", engines=n)
        self.ensure_alive()
        self._wake.set()
        return engine

    def detach(self, engine: Any) -> None:
        with self._lock:
            if engine in self._engines:
                self._engines.remove(engine)
            if getattr(engine, "_drain_owner", None) is self:
                engine._drain_owner = None

    def is_drain_thread(self) -> bool:
        return threading.current_thread() is self._thread

    def kick(self) -> None:
        self._wake.set()

    def ensure_alive(self) -> None:
        """(Re)start the shared drain; the restart path is the thread-death latch."""
        with self._lock:
            t = self._thread
            if t is not None and t.is_alive():
                return
            if t is not None:
                self.restarts += 1
                telemetry.counter("serve.drain_restarts").inc()
                _flightrec.record(
                    "control.shared_drain_restart", restarts=self.restarts,
                    engines=len(self._engines),
                )
            self._stop = False
            self._thread = threading.Thread(target=self._loop, daemon=True, name=self.name)
            self._thread.start()

    def close(self) -> None:
        with self._lock:
            self._stop = True
            t = self._thread
        self._wake.set()
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)

    def _weight(self, engine: Any) -> float:
        w = 1.0 + min(1.0, engine.inflight / float(engine.options.max_inflight))
        ctrl = getattr(engine, "_control", None)
        if ctrl is not None:
            try:
                w += ctrl.shed_burn(engine)
            except ServeError:
                pass  # engine raced a detach; base weight still serves it
        return w

    def _loop(self) -> None:
        _trace.note_thread("serve-shared-drain")
        while True:
            with self._lock:
                if self._stop:
                    return
                engines = list(self._engines)
            if not engines:
                self._wake.wait(0.05)
                self._wake.clear()
                continue
            progressed = False
            for eng in engines:
                credit = min(
                    self.deficit_cap,
                    self._deficit.get(id(eng), 0.0) + self.quantum * self._weight(eng),
                )
                while credit >= 1.0:
                    outcome = eng._drain_once(wait=False)
                    if outcome == "applied":
                        credit -= 1.0
                        progressed = True
                        continue
                    if outcome == "killed":
                        # the chaos kill semantics: this thread genuinely dies; the
                        # next ensure_alive (quiesce/enqueue) revives it
                        self._deficit[id(eng)] = credit
                        return
                    if outcome == "stop":
                        credit = 0.0
                    break
                self._deficit[id(eng)] = credit
            if not progressed:
                self._wake.wait(0.005)
                self._wake.clear()


# ---------------------------------------------------------------------------
# drift-triggered auto-snapshot
# ---------------------------------------------------------------------------

class DriftSnapshotter:
    """Every detected shift gets a checkpoint to diff against (docs/online.md).

    Subscribes to a :class:`~torchmetrics_tpu.online.drift.DriftMonitor`: while the
    detectors are quiet, each :meth:`poll` refreshes a rolling host-side *pre-shift*
    snapshot; the evaluation that transitions a spec into ``drifting`` durably lands
    the pre-shift blob and an at-alarm blob (``robust.checkpoint`` format, CRC'd),
    opens an incident, records ``drift.auto_snapshot``, and captures a post-mortem
    bundle. De-escalation (the alarm clearing) re-arms the capture.
    """

    def __init__(self, metric: Any, monitor: Any, outdir: str) -> None:
        self.metric = metric
        self.monitor = monitor
        self.outdir = os.fspath(outdir)
        os.makedirs(self.outdir, exist_ok=True)
        self._healthy_blob: Optional[Dict[str, Any]] = None
        self._firing: set = set()
        self.captured: List[Dict[str, Any]] = []
        monitor.subscribe(self._on_transition)

    def poll(self, now: Optional[float] = None) -> List[Any]:
        """Evaluate the monitor (transitions fire captures via the subscription),
        then refresh the pre-shift snapshot while everything is quiet."""
        statuses = self.monitor.evaluate(now=now)
        if not self._firing:
            from torchmetrics_tpu.robust import checkpoint as _checkpoint

            self._healthy_blob = _checkpoint.snapshot_metric(self.metric)
        return statuses

    def _on_transition(self, status: Any, firing: bool) -> None:
        name = status.spec.name
        if not firing:
            self._firing.discard(name)
            return
        if name in self._firing:
            return
        self._firing.add(name)
        self._capture(status)

    def _capture(self, status: Any) -> Dict[str, Any]:
        from torchmetrics_tpu.robust import checkpoint as _checkpoint

        name = status.spec.name
        incident = _flightrec.open_incident(f"drift_shift.{name}")
        base = os.path.join(self.outdir, f"{name}-{len(self.captured)}")
        paths: Dict[str, str] = {}
        if self._healthy_blob is not None:
            paths["pre_shift"] = _checkpoint.save_snapshot(
                self._healthy_blob, base + "-pre.tmsnap"
            )
        paths["at_alarm"] = _checkpoint.save_snapshot(
            _checkpoint.snapshot_metric(self.metric), base + "-alarm.tmsnap"
        )
        _flightrec.record(
            "drift.auto_snapshot", name=name, incident=incident,
            score=None if status.score is None else round(float(status.score), 6),
            pre_shift="pre_shift" in paths,
        )
        telemetry.counter("control.drift_snapshots").inc()
        bundle_path = _bundle.capture_bundle(f"drift_shift.{name}", metric=self.metric)
        record = {
            "name": name, "incident": incident, "score": status.score,
            "paths": paths, "bundle": bundle_path,
        }
        self.captured.append(record)
        return record
