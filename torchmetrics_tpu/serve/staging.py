"""Double-buffered host→device staging for the async ingestion tier.

``jax.device_put`` is asynchronous: it enqueues the host→device copy and returns a
future-backed array immediately, so a transfer issued at *enqueue* time executes while
the device is still busy with the previous donated update step (the overlap the TPU
serving pipelines get from their input double-buffer). The pipeline here adds the two
things raw ``device_put`` lacks for a serving loop:

- **pinned slots** — each staged batch's arrays are held in one of ``n_slots`` slot
  lists until the drain commits that batch, so the transfer's backing buffers cannot be
  released mid-copy and transfer-ahead memory is capped at ``n_slots`` batches (the
  classic double buffer at the default ``n_slots=2``: one batch transferring while the
  previous one computes).
- **graceful degradation** — slot exhaustion (the drain fell behind) skips staging and
  hands the host arrays through untouched (the drain's own dispatch will move them:
  correctness never depends on the overlap); a *failed* transfer
  (:class:`~torchmetrics_tpu.robust.chaos.StagingTransferFailure`) is absorbed the same
  way, counted in ``serve.staging_fallbacks`` with a one-shot rank-zero warning.

Values are never changed by staging — a staged leaf is the same array on a different
buffer — so every bit-identity contract of the engine holds with staging on or off.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import jax

from torchmetrics_tpu.obs import telemetry
from torchmetrics_tpu.utils.prints import rank_zero_warn

#: module-level seam the chaos harness patches (StagingTransferFailure); the pipeline
#: always transfers through this name, never through ``jax.device_put`` directly
device_put = jax.device_put


def _stageable(leaf: Any) -> bool:
    """Array-shaped leaves move; host scalars/strings/None pass through untouched."""
    return hasattr(leaf, "shape") and hasattr(leaf, "dtype")


class StagingPipeline:
    """Bounded transfer-ahead staging: stage opportunistically, pin until committed."""

    def __init__(self, n_slots: int = 2, device: Optional[Any] = None) -> None:
        self.n_slots = max(1, int(n_slots))
        self.device = device
        self._lock = threading.Lock()
        self._slots: Dict[int, List[Any]] = {}
        self._free: List[int] = list(range(self.n_slots))
        self._warned_fallback = False

    def stage(self, args: tuple, kwargs: dict) -> Tuple[tuple, dict, Optional[int]]:
        """Start the host→device copies for one batch; returns (args, kwargs, slot).

        ``slot`` is ``None`` when staging was skipped (no free slot) or degraded (a
        transfer failed); either way the returned batch is usable as-is.
        """
        with self._lock:
            slot = self._free.pop() if self._free else None
        if slot is None:
            telemetry.counter("serve.staging_skips").inc()
            return args, kwargs, None
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        try:
            # ONE device_put over the stageable leaves: per-call Python dispatch
            # overhead (~tens of us) would otherwise be paid per leaf per request,
            # which at serving rates costs more than the transfer itself
            idx = [i for i, leaf in enumerate(leaves) if _stageable(leaf)]
            moved = device_put([leaves[i] for i in idx], self.device) if idx else []
            staged = list(leaves)
            for i, arr in zip(idx, moved):
                staged[i] = arr
        except Exception as err:
            # transfer failure (chaos: StagingTransferFailure, or a sick device): the
            # host batch is still valid — hand it through and let the drain's own
            # dispatch do the move; the serving tier degrades, it does not drop data
            self.release(slot)
            telemetry.counter("serve.staging_fallbacks").inc()
            if not self._warned_fallback:
                self._warned_fallback = True
                rank_zero_warn(
                    f"Host->device staging transfer failed ({err!r}); the ingestion tier"
                    " is falling back to unstaged host batches (correct but unoverlapped).",
                    UserWarning,
                )
            return args, kwargs, None
        with self._lock:
            self._slots[slot] = staged  # pin: buffers live until the drain commits
        telemetry.counter("serve.staged_batches").inc()
        s_args, s_kwargs = jax.tree_util.tree_unflatten(treedef, staged)
        return s_args, s_kwargs, slot

    def release(self, slot: Optional[int]) -> None:
        """Unpin a committed batch's slot, making it available to the next enqueue."""
        if slot is None:
            return
        with self._lock:
            self._slots.pop(slot, None)
            if slot not in self._free:
                self._free.append(slot)

    @property
    def in_use(self) -> int:
        with self._lock:
            return self.n_slots - len(self._free)
