"""The async ingestion engine: bounded in-flight window, FIFO drain, quiesce contract.

``Metric.update_async`` enqueues a batch and returns an :class:`IngestTicket` future; a
single background drain thread applies enqueued batches strictly FIFO through the
metric's ordinary synchronous dispatch tiers (jit / AOT+donation / keyed / sharded — the
tiers the tier-equivalence and chaos suites already prove bit-identical). Because the
drain is the ONLY mutator while the window is non-empty, the engine needs no per-state
locking: every host access path (``update``/``forward``/``compute``/``snapshot``/
``sync``/``reset``) quiesces the window first, so user code only ever observes a fully
drained, exact state.

Throughput comes from two overlaps plus one structural win: the staging transfer runs
in the caller while the previous window computes; the caller's host work (request
decode) runs while the drain dispatches; and when traffic bursts ahead of the drain,
consecutive same-shape batches in the window are COALESCED through one
``update_batches`` scan launch (``ServeOptions(coalesce=k)``) — k dispatches become
one, which a synchronous per-batch loop structurally cannot do. Coalescing changes
launch shape only, never values (the scan tier is bit-identical with the sequential
loop), and strictly preserves FIFO.

Crash consistency (docs/serving.md "WAL contract"): when a journal is attached, the
batch is appended durably at *enqueue* time — before it is even pending in memory — so a
preemption mid-overlap loses nothing: ``snapshot + replay(journal)`` re-drives the exact
committed-plus-pending stream through the synchronous path, bit-identically.

Fault latches (driven by the chaos injectors in ``torchmetrics_tpu.robust.chaos``):

- **drain-thread death** (:class:`DrainThreadDeath`): the in-hand ticket is returned to
  the window head before the thread dies; the next quiesce/enqueue detects the dead
  thread, restarts it (``serve.drain_restarts``), and the restarted drain re-applies
  from the window — no batch applied twice, none lost.
- **queue overflow** (:class:`QueueOverflow`): the bounded window turns overflow into
  the configured backpressure (block / raise / shed) instead of unbounded growth.
- **staging transfer failure** (:class:`StagingTransferFailure`): absorbed inside
  :class:`~torchmetrics_tpu.serve.staging.StagingPipeline` — unstaged host batches,
  same values.
- **apply failure**: the failing ticket records its error AND the engine latches it;
  the next quiesce raises :class:`ServeError` so a ``compute()`` can never silently
  omit a batch the caller believes was ingested.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Optional, Tuple

from torchmetrics_tpu.obs import bundle as _bundle
from torchmetrics_tpu.obs import flightrec as _flightrec
from torchmetrics_tpu.obs import telemetry
from torchmetrics_tpu.obs import trace as _trace
from torchmetrics_tpu.ops import dispatch as _dispatch
from torchmetrics_tpu.serve.options import ServeOptions
from torchmetrics_tpu.serve.staging import StagingPipeline
from torchmetrics_tpu.utils.exceptions import BackpressureError, ServeError
from torchmetrics_tpu.utils.prints import rank_zero_warn

#: initial/backoff-capped park times for a blocking enqueue (jittered between them)
_BLOCK_WAIT_MIN_S = 0.001
_BLOCK_WAIT_MAX_S = 0.25


def _jittered_wait(prev: float) -> float:
    """Next decorrelated-jitter park time for a blocked producer.

    ``min(cap, uniform(base, prev * 3))`` — the AWS "decorrelated jitter" recurrence,
    same shape as the cross-process sync backoff. Sharing the seam matters: the RNG is
    the chaos-seeded one (``TM_TPU_CHAOS_SEED`` / ``reset_backoff_rng``), so chaos runs
    replay the exact park sequence, and many producers blocked on one full window wake
    scattered instead of retrying in lockstep.
    """
    from torchmetrics_tpu.parallel.sync import _backoff_rng

    return min(
        _BLOCK_WAIT_MAX_S,
        _backoff_rng().uniform(_BLOCK_WAIT_MIN_S, max(_BLOCK_WAIT_MIN_S, prev * 3.0)),
    )


class DrainKilled(BaseException):
    """Chaos-only: simulates the drain thread dying between dequeue and apply.

    A ``BaseException`` so the ordinary apply-failure handler (which absorbs
    ``Exception``) cannot catch it — the thread genuinely terminates, exactly like an
    external kill, and recovery must go through the restart latch.
    """


class IngestTicket:
    """Lightweight future for one enqueued batch.

    ``wait``/``result`` resolve when the drain commits (or fails/sheds) the batch;
    ``generation`` is the :class:`StateStore` generation the commit landed at (the
    fence readers can compare against ``Metric.state_generation``). ``trace_id`` is the
    per-ticket trace/span id minted at enqueue while telemetry is enabled (None
    otherwise) — the flow-event id linking the caller's enqueue slice to the drain
    thread's commit in the exported Perfetto trace (docs/observability.md).
    """

    __slots__ = ("seq", "shed", "error", "generation", "trace_id", "_event")

    def __init__(self, seq: int) -> None:
        self.seq = seq
        self.shed = False
        self.error: Optional[BaseException] = None
        self.generation: Optional[int] = None
        self.trace_id: Optional[int] = None
        self._event = threading.Event()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> Optional[int]:
        """Block until resolved; raise the apply error if one fired, else return the
        committed state generation (``None`` for a shed ticket)."""
        if not self._event.wait(timeout):
            raise BackpressureError(
                f"IngestTicket #{self.seq} unresolved after {timeout}s"
            )
        if self.error is not None:
            raise self.error
        return self.generation

    def _resolve(self, generation: Optional[int] = None, error: Optional[BaseException] = None) -> None:
        self.generation = generation
        self.error = error
        self._event.set()

    def __repr__(self) -> str:
        state = "shed" if self.shed else ("done" if self.done() else "pending")
        return f"IngestTicket(seq={self.seq}, {state})"


class IngestEngine:
    """One metric's (or collection's) async ingestion window + drain thread."""

    def __init__(self, target: Any, options: Optional[ServeOptions] = None,
                 journal: Optional[Any] = None) -> None:
        self.target = target
        self.options = options or ServeOptions()
        self.journal = journal
        #: attached ServeController (the adaptive actuator tier) and/or SharedDrain
        #: owner (one drain thread serving many engines); None = static/per-engine
        self._control: Optional[Any] = None
        self._drain_owner: Optional[Any] = None
        self._staging = StagingPipeline(self.options.staging_slots)
        self._cond = threading.Condition()
        self._queue: Deque[Tuple[IngestTicket, tuple, dict, Optional[int]]] = deque()
        self._applying_n = 0  # batches popped from the queue and not yet committed
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._paused = False
        self._flush = False  # a quiescer is waiting: bypass the linger dwell
        self._abandoned = False
        self._seq = 0
        self._fence: Optional[int] = None  # StateStore generation after the last commit
        self._pending_error: Optional[BaseException] = None
        self._stats = {
            "enqueued": 0, "committed": 0, "shed": 0, "failed": 0,
            "drain_restarts": 0, "fence_breaks": 0, "backpressure_stalls": 0,
            "online_advances": 0,
        }

    # ------------------------------------------------------------------ window state
    @property
    def inflight(self) -> int:
        """Enqueued-but-uncommitted batches (including those being applied)."""
        with self._cond:
            return len(self._queue) + self._applying_n

    def stats(self) -> Dict[str, int]:
        with self._cond:
            out = dict(self._stats)
            out["inflight"] = len(self._queue) + self._applying_n
        return out

    # ---------------------------------------------------------------------- enqueue
    def enqueue(self, args: tuple, kwargs: dict) -> IngestTicket:
        """Stage one batch into the bounded window; returns its ticket.

        Journal append happens FIRST (write-ahead at enqueue time), then window
        admission under the ``on_full`` policy, then the staging transfer — so a batch
        that sheds was still journaled (replay reproduces the *offered* stream; the
        shed count says which suffix of it the live state dropped).
        """
        if self._abandoned:
            raise ServeError("This IngestEngine was abandoned (chaos preemption); build a fresh metric")
        wal_seq = self.journal.append(args, kwargs) if self.journal is not None else None
        ticket = self._admit(args, kwargs, wal_seq)
        owner = self._drain_owner
        if owner is not None:
            owner.kick()
        return ticket

    def attach_controller(self, control: Any) -> None:
        """Bind a :class:`~torchmetrics_tpu.serve.control.ServeController` (its
        :meth:`attach` calls this); the drain reads dwell/coalesce through it and the
        admission path consults its block→timed→shed ladder."""
        with self._cond:
            self._control = control

    def _resolve_shed_locked(self, ticket: IngestTicket, reason: str = "window_full") -> IngestTicket:
        """Shed one offered batch (caller holds ``_cond``): resolve + count + events."""
        opts = self.options
        ticket.shed = True
        ticket._resolve()
        self._stats["shed"] += 1
        telemetry.counter("serve.shed").inc()
        telemetry.counter("robust.shed_batches").inc()
        # always-on live series (docs/observability.md "Live time series"):
        # queue_depth records one point per OFFERED batch (the shed-ratio
        # denominator), serve.sheds the shed events themselves
        telemetry.series("serve.queue_depth").record(opts.max_inflight)
        telemetry.series("serve.sheds").record(1.0)
        _flightrec.record(
            "serve.shed", seq=ticket.seq, inflight=opts.max_inflight, reason=reason
        )
        _trace.shed_event(ticket.trace_id, ticket.seq)
        rank_zero_warn(
            f"Async ingestion window full ({opts.max_inflight} in flight):"
            f" shedding batches ({reason}). Shed counts are exact in"
            " serve.shed / IngestEngine.stats().",
            UserWarning,
        )
        return ticket

    def _admit(self, args: tuple, kwargs: dict, wal_seq: Optional[int] = None) -> IngestTicket:
        opts = self.options
        # one flag read on the tracing-disabled path (the <=2us bound obs-smoke pins)
        t0_us = telemetry.now_us() if telemetry.enabled else 0.0
        with self._cond:
            self._ensure_drain_locked()
            ticket = IngestTicket(self._seq)
            self._seq += 1
            ctrl = self._control
            if self._window_full_locked():
                if opts.on_full == "raise":
                    raise BackpressureError(
                        f"Async ingestion window full ({opts.max_inflight} in flight)"
                        " and on_full='raise'"
                    )
                if opts.on_full == "shed":
                    mode, park_s = "shed", 0.0
                elif ctrl is not None:
                    # the escalating admission ladder: the controller may have moved a
                    # block engine to timed-block (shorter park budget) or shed
                    mode, park_s = ctrl.admission(self)
                else:
                    mode, park_s = "block", opts.queue_timeout_s
                if mode == "shed":
                    self._resolve_shed_locked(
                        ticket,
                        reason="on_full='shed'" if opts.on_full == "shed" else "admission=shed",
                    )
                    if ctrl is not None:
                        ctrl.note_offered(self, opts.max_inflight, shed=True, wal_seq=wal_seq)
                    return ticket
                # block / timed-block: park with decorrelated-jitter waits against the
                # rung's budget (chaos-seeded RNG — producers wake scattered, replayable)
                self._stats["backpressure_stalls"] += 1
                telemetry.counter("serve.backpressure_stalls").inc()
                _flightrec.record(
                    "serve.backpressure", seq=ticket.seq, inflight=opts.max_inflight,
                    mode=mode,
                )
                park_start = time.monotonic()
                wait = _BLOCK_WAIT_MIN_S
                while self._window_full_locked():
                    self._ensure_drain_locked()
                    if ctrl is not None:
                        # re-read the rung each wakeup: an escalation to shed releases
                        # every parked producer instead of letting them burn the budget
                        mode, park_s = ctrl.admission(self)
                        if mode == "shed":
                            self._resolve_shed_locked(ticket, reason="admission=shed")
                            ctrl.note_offered(self, opts.max_inflight, shed=True, wal_seq=wal_seq)
                            return ticket
                    remaining = park_start + park_s - time.monotonic()
                    if remaining <= 0:
                        telemetry.counter("serve.queue_timeouts").inc()
                        if ctrl is not None:
                            # with a controller attached an exhausted park budget sheds
                            # (a journaled, replayable decision) instead of raising —
                            # graceful degradation end to end
                            self._resolve_shed_locked(ticket, reason=f"{mode}_budget_exhausted")
                            ctrl.note_offered(self, opts.max_inflight, shed=True, wal_seq=wal_seq)
                            return ticket
                        raise BackpressureError(
                            f"Async ingestion enqueue blocked past queue_timeout_s="
                            f"{opts.queue_timeout_s:g}s with {opts.max_inflight} in flight"
                            " (is the drain stalled?)"
                        )
                    wait = _jittered_wait(wait)
                    self._cond.wait(min(wait, remaining))
            s_args, s_kwargs, slot = self._staging.stage(args, kwargs)
            # the trace id must exist BEFORE the batch is visible to the drain: the
            # commit's flow-end reads it, possibly before this thread leaves the lock.
            # Guarded here (not just inside mint) so the disabled path pays one flag
            # read, not a function call — the <=2us/enqueue budget is tight.
            if telemetry.enabled:
                ticket.trace_id = _trace.mint()
            self._queue.append((ticket, s_args, s_kwargs, slot, time.monotonic()))
            self._stats["enqueued"] += 1
            depth = len(self._queue) + self._applying_n
            if ctrl is not None:
                # one controller tick per offered batch — the decision clock
                ctrl.note_offered(self, depth, shed=False, wal_seq=wal_seq)
            self._cond.notify_all()
        telemetry.counter("serve.enqueued").inc()
        telemetry.histogram("serve.queue_depth").record(depth)
        # ONE always-on series record per enqueue (the <=2us disabled-path budget):
        # each point is the live depth, so the series doubles as the offered-event
        # stream — rate_over() is the enqueue rate, the SLO shed-ratio denominator
        telemetry.series("serve.queue_depth").record(depth)
        if ticket.trace_id is not None:
            _trace.enqueue_span(ticket.trace_id, t0_us, ticket.seq, depth, slot)
        return ticket

    def _window_full_locked(self) -> bool:
        return len(self._queue) + self._applying_n >= self.options.max_inflight

    # ------------------------------------------------------------------------ drain
    def _ensure_drain_locked(self) -> None:
        """(Re)start the drain thread; the restart path is the thread-death latch."""
        owner = self._drain_owner
        if owner is not None:
            # a SharedDrain owns this engine: its restart latch covers thread death
            # for the whole fleet of attached engines; no per-engine thread exists
            owner.ensure_alive()
            owner.kick()
            return
        t = self._thread
        if t is not None and t.is_alive():
            return
        if t is not None:  # a previous drain died (chaos DrainThreadDeath, or a crash)
            if not self.options.restart_drain:
                # incident first: the drain-death flight events must carry the id the
                # bundle (and the federation gossip) will advertise
                _flightrec.open_incident("serve_drain_death")
                _flightrec.record(
                    "serve.drain_restart", pending=len(self._queue), restarted=False
                )
                _bundle.capture_bundle("serve_drain_death", metric=self.target)
                raise ServeError(
                    "The ingestion drain thread died and restart_drain is off; the"
                    f" window holds {len(self._queue)} unapplied batch(es)."
                )
            self._stats["drain_restarts"] += 1
            telemetry.counter("serve.drain_restarts").inc()
            # a drain death is a real failure seam even when the latch recovers it:
            # land the post-mortem bundle, then restart (docs/observability.md)
            _flightrec.open_incident("serve_drain_death")
            _flightrec.record(
                "serve.drain_restart", pending=len(self._queue),
                restarts=self._stats["drain_restarts"],
            )
            _bundle.capture_bundle("serve_drain_death", metric=self.target)
            rank_zero_warn(
                "The async ingestion drain thread died; restarting it. Batches still in"
                " the window will be re-applied in FIFO order (none were committed).",
                UserWarning,
            )
        self._stop = False
        self._thread = threading.Thread(
            target=self._drain_loop, daemon=True, name="tm-tpu-serve-drain"
        )
        self._thread.start()

    def _effective_linger_s(self) -> float:
        """Live micro-batching dwell: the controller's actuator position when one is
        attached, else the static option — re-read every window, not once per loop."""
        ctrl = self._control
        if ctrl is not None:
            return ctrl.linger_ms(self) / 1000.0
        return self.options.linger_ms / 1000.0

    def _effective_coalesce(self) -> int:
        ctrl = self._control
        if ctrl is not None:
            return int(ctrl.coalesce(self))
        return self.options.coalesce

    def _is_drain_thread(self) -> bool:
        """Is the current thread the one draining this engine (own or shared)?"""
        if threading.current_thread() is self._thread:
            return True
        owner = self._drain_owner
        return owner is not None and owner.is_drain_thread()

    def _drain_loop(self) -> None:
        _trace.note_thread("serve-drain")  # label this track in the exported trace
        while True:
            if self._drain_once(wait=True) in ("stop", "killed"):
                return

    def _drain_once(self, wait: bool = True) -> str:
        """Apply at most one coalesced window; returns the outcome.

        ``"applied"`` — a window left the queue (committed or failed); ``"idle"`` —
        nothing ready (empty/paused, or a non-blocking call found the linger dwell
        still running); ``"stop"`` — the engine is stopping and the queue is empty;
        ``"killed"`` — chaos :class:`DrainKilled` fired and the calling thread must
        terminate. ``wait=True`` is the dedicated-drain mode (blocks for work and
        dwells in-lock); ``wait=False`` is the :class:`SharedDrain` quantum — never
        blocks, so one thread can round-robin many engines.
        """
        linger_s = self._effective_linger_s()
        coalesce = self._effective_coalesce()
        with self._cond:
            if wait:
                while (not self._queue or self._paused) and not self._stop:
                    self._cond.wait()
            if self._stop and not self._queue:
                return "stop"
            if (self._paused and not self._stop) or not self._queue:
                return "idle"
            if linger_s > 0 and not (self._flush or self._stop):
                # micro-batching dwell: give the enqueueing thread up to linger_ms
                # to fill a coalescible window before launching (bypassed the
                # moment a quiescer waits or the window is already full-width)
                if wait:
                    while (
                        0 < len(self._queue) < coalesce
                        and not (self._flush or self._stop or self._paused)
                    ):
                        remaining = self._queue[0][4] + linger_s - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                    if not self._queue or self._paused:
                        return "idle"
                elif (
                    0 < len(self._queue) < coalesce
                    and self._queue[0][4] + linger_s - time.monotonic() > 0
                ):
                    return "idle"  # dwell unexpired; the shared drain comes back
            items = [self._queue.popleft()]
            if coalesce > 1 and self._queue:
                # coalesce consecutive same-shape batches into one scan launch:
                # k dispatches become 1 (the update_batches tier), FIFO preserved.
                # Widths are quantized to powers of two so the compiled stacked-scan
                # signatures stay bounded at log2(coalesce) shapes — an arbitrary
                # width would AOT-compile a fresh scan per distinct burst size.
                key0 = _dispatch._batch_key(items[0][1], items[0][2])
                while self._queue and len(items) < coalesce:
                    head = self._queue[0]
                    if _dispatch._batch_key(head[1], head[2]) != key0:
                        break
                    items.append(self._queue.popleft())
                width = 1 << (len(items).bit_length() - 1)
                while len(items) > width:  # hand the overshoot back, order intact
                    self._queue.appendleft(items.pop())
            self._applying_n = len(items)
            inflight_now = len(self._queue) + self._applying_n
        width = len(items)
        tier = "update" if width == 1 else "update_batches"
        telemetry.series("serve.inflight").record(inflight_now)
        t_apply0 = 0.0
        if telemetry.enabled:
            t_apply0 = telemetry.now_us()
            for it in items:
                if width > 1:
                    _trace.coalesced_event(it[0].trace_id, width)
                _trace.dispatched_event(it[0].trace_id, tier, width)
        try:
            self._apply_window(items)
        except DrainKilled:
            # the thread is dying between dequeue and apply: hand the window back
            # (nothing was committed) so the restart latch re-applies it FIFO, then
            # terminate without the default excepthook spew — the death is
            # observable via the dead thread, exactly like an external kill
            with self._cond:
                self._queue.extendleft(reversed(items))
                self._applying_n = 0
                self._cond.notify_all()
            for it in items:
                self._staging.release(it[3])
            return "killed"
        except Exception as err:  # noqa: BLE001 - a bad batch must not kill the drain
            telemetry.counter("serve.apply_failures").inc(len(items))
            _flightrec.record(
                "serve.apply_failure", batches=len(items), error=repr(err)[:200]
            )
            for it in items:
                it[0]._resolve(error=err)
                _trace.failed_event(it[0].trace_id, repr(err))
            with self._cond:
                # stats share _cond with the admission counters: the main thread
                # bumps "enqueued"/"shed" under it, so the drain's failure count
                # must too or the += load/store pair loses updates (TPU021)
                self._stats["failed"] += len(items)
                if self._pending_error is None:
                    self._pending_error = err
                self._applying_n = 0
                self._cond.notify_all()
        else:
            telemetry.counter("serve.committed").inc(len(items))
            if len(items) > 1:
                telemetry.counter("serve.coalesced_launches").inc()
            # always-on: commit-event + enqueue->commit latency series (the SLO
            # commit-latency feed), then the trace closes each ticket's flow on
            # THIS (drain) thread — the caller->drain link Perfetto draws
            now_mono = time.monotonic()
            lat_series = telemetry.series("serve.commit_latency_us")
            commits = telemetry.series("serve.commits")
            for it in items:
                lat_series.record((now_mono - it[4]) * 1e6)
                commits.record(1.0)
            if telemetry.enabled:
                _trace.apply_span(t_apply0, width, tier)
                for it in items:
                    _trace.committed_event(
                        it[0].trace_id, (now_mono - it[4]) * 1e6, it[0].generation
                    )
            with self._cond:
                self._stats["committed"] += len(items)
                self._applying_n = 0
                if self._control is not None:
                    # commits relieve pressure between offered ticks; let the next
                    # decision see the drained depth, not the pre-commit burst
                    self._control.note_committed(self, len(items))
                self._cond.notify_all()
        finally:
            for it in items:
                self._staging.release(it[3])
        return "applied"

    def _apply_window(self, items: list) -> None:
        """Apply one FIFO window of batches through the target's synchronous tiers.

        A single batch drives ``update``; a coalesced window stacks the batches and
        drives ``update_batches`` (the compiled scan tier — bit-identical with the
        sequential loop by the tier-equivalence contract). The generation fence:
        between two drain commits nothing else may move the target's
        :class:`StateStore` generation — a move means some other thread mutated state
        while the window was non-empty (a quiesce-contract violation), which is
        counted and warned, never silent.
        """
        store = getattr(self.target, "_state", None)
        if store is not None and self._fence is not None and store.generation != self._fence:
            with self._cond:  # stats share _cond with the main thread's admission counters
                self._stats["fence_breaks"] += 1
            telemetry.counter("serve.fence_breaks").inc()
            _flightrec.record(
                "serve.fence_break", expected=self._fence, observed=store.generation
            )
            _trace.fence_break_event(self._fence, store.generation)
            rank_zero_warn(
                "Async ingestion generation fence broke: the metric state moved"
                f" (generation {self._fence} -> {store.generation}) while batches were"
                " in flight. Some non-drain code mutated state without quiescing the"
                " window first.",
                UserWarning,
            )
        # windowed targets (torchmetrics_tpu.online) advance their ring in-graph as
        # the drain applies batches (update-count ticks — deterministic under WAL
        # replay); the host-side advance counter diff attributes those advances to
        # the drain without any device read
        advances_before = getattr(self.target, "windows_advanced", None)
        if len(items) == 1:
            args, kwargs = items[0][1], items[0][2]
            self.target.update(*args, **kwargs)
        else:
            import jax.numpy as jnp

            first_args, first_kwargs = items[0][1], items[0][2]
            stacked_args = tuple(
                jnp.stack([it[1][i] for it in items]) for i in range(len(first_args))
            )
            stacked_kwargs = {
                name: jnp.stack([it[2][name] for it in items]) for name in first_kwargs
            }
            self.target.update_batches(*stacked_args, **stacked_kwargs)
        if advances_before is not None:
            advanced = self.target.windows_advanced - advances_before
            if advanced > 0:
                with self._cond:
                    self._stats["online_advances"] += advanced
                telemetry.counter("serve.online_advances").inc(advanced)
        gen = store.generation if store is not None else None
        # Sole-writer protocol, not a lock: while batches are in flight only the drain
        # advances the fence, and quiesce() only clears it after the window is provably
        # empty (it holds _cond and waited for _queue and _applying_n to hit zero) — so
        # the two writers are separated by the quiesce barrier, never overlapped.
        self._fence = gen  # jaxlint: single-mutator (racerun: engine_enqueue_vs_quiesce)
        for it in items:
            it[0]._resolve(generation=gen)

    # ---------------------------------------------------------------------- quiesce
    def quiesce(self, timeout: Optional[float] = None) -> None:
        """Block until the window is empty (called by every host access path).

        No-op from the drain thread itself (the drain calling ``target.update`` must
        not wait on its own queue). Restarts a dead drain when batches are pending;
        re-raises the first deferred apply error so a drained state is either exact or
        loudly incomplete — never silently short.
        """
        if self._is_drain_thread():
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            self._flush = True  # bypass the linger dwell: a reader is waiting
            self._cond.notify_all()
            try:
                while self._queue or self._applying_n:
                    self._ensure_drain_locked()
                    if deadline is not None and time.monotonic() >= deadline:
                        raise ServeError(
                            f"quiesce timed out with {len(self._queue)} batch(es) still in"
                            " the ingestion window"
                        )
                    self._cond.wait(0.05)
            finally:
                self._flush = False
            # an empty window means user code may mutate state freely until the next
            # enqueue; drop the fence so legitimate post-quiesce mutations don't trip it
            self._fence = None
            err, self._pending_error = self._pending_error, None
        if err is not None:
            # the deferred apply failure surfaces HERE (the drain already recorded the
            # apply_failure event); capture the bundle before the raise reaches user code
            _flightrec.open_incident("serve_apply_failure")
            _bundle.capture_bundle("serve_apply_failure", metric=self.target)
            raise ServeError(
                f"A batch enqueued via update_async failed to apply: {err!r}. The"
                " metric state holds every batch before it; the failed batch is NOT"
                " included."
            ) from err

    # ------------------------------------------------------------- chaos/test seams
    def pause(self) -> None:
        """Hold the drain (QueueOverflow chaos: fills the window deterministically)."""
        with self._cond:
            self._paused = True
            self._cond.notify_all()

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def abandon(self) -> int:
        """Chaos preemption: drop the engine cold, window and all; returns the number of
        batches that were in flight. The journal (appended at enqueue) is the only
        survivor — recovery is ``snapshot + replay(journal)`` on a FRESH metric."""
        with self._cond:
            dropped = len(self._queue) + self._applying_n
            for it in self._queue:  # close every in-window flow: no dangling trace ids
                _trace.abandoned_event(it[0].trace_id)
            self._queue.clear()
            self._paused = False
            self._stop = True
            self._abandoned = True
            self._cond.notify_all()
        # the preemption seam: the dropped window only survives in the write-ahead
        # journal, and the bundle records its cursor — post-mortem replay from it is
        # bit-identical (docs/observability.md "Flight recorder & post-mortem bundles")
        _flightrec.open_incident("serve_abandoned")
        _flightrec.record("serve.abandoned", dropped_in_window=dropped)
        _bundle.capture_bundle("serve_abandoned", metric=self.target)
        return dropped

    def close(self) -> None:
        """Drain outstanding batches, then stop the thread (idempotent)."""
        self.quiesce()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        owner = self._drain_owner
        if owner is not None:
            # the shared thread keeps serving its other engines; just stop being one
            owner.detach(self)
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
