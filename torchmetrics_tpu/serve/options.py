"""Serving-tier policy: the bounded in-flight window and its on-full semantics.

The bound is the robustness property: an unbounded enqueue path turns a traffic spike
into host-RAM/HBM exhaustion, a bounded one turns it into *backpressure* — the caller
blocks, errors, or sheds, and the engine's memory footprint stays ``O(max_inflight +
staging_slots)`` batches whatever the arrival rate does. ``on_full`` picks the contract:

==========  =========================================================================
``block``   park the caller with exponential-backoff waits until a slot frees; give up
            with :class:`~torchmetrics_tpu.utils.exceptions.BackpressureError` after
            ``queue_timeout_s`` (a stuck drain must not wedge the service forever)
``raise``   fail the enqueue immediately with :class:`BackpressureError` (the caller
            owns the retry/shed policy)
``shed``    drop the batch, count it (``serve.shed`` / ``robust.shed_batches``), warn
            once rank-zero, and return a ticket marked ``shed`` — graceful degradation
==========  =========================================================================

Env knobs (read by :func:`serve_options_from_env`, the default when ``update_async`` is
called on an unconfigured metric): ``TM_TPU_SERVE_MAX_INFLIGHT``, ``TM_TPU_SERVE_ON_FULL``,
``TM_TPU_SERVE_QUEUE_TIMEOUT_S``, ``TM_TPU_SERVE_STAGING_SLOTS``.
"""
from __future__ import annotations

import os
from dataclasses import dataclass

from torchmetrics_tpu.utils.exceptions import ServeError

ENV_SERVE_MAX_INFLIGHT = "TM_TPU_SERVE_MAX_INFLIGHT"
ENV_SERVE_ON_FULL = "TM_TPU_SERVE_ON_FULL"
ENV_SERVE_QUEUE_TIMEOUT = "TM_TPU_SERVE_QUEUE_TIMEOUT_S"
ENV_SERVE_STAGING_SLOTS = "TM_TPU_SERVE_STAGING_SLOTS"
ENV_SERVE_COALESCE = "TM_TPU_SERVE_COALESCE"
ENV_SERVE_LINGER = "TM_TPU_SERVE_LINGER_MS"

_ON_FULL = ("block", "raise", "shed")


@dataclass(frozen=True)
class ServeOptions:
    """Policy for one :class:`~torchmetrics_tpu.serve.engine.IngestEngine`.

    ``max_inflight`` bounds enqueued-but-uncommitted batches (the in-flight window,
    including the batch the drain thread is currently applying). ``queue_timeout_s``
    caps how long one blocking enqueue may park. ``staging_slots`` sizes the
    double-buffered host→device staging pipeline (transfer-ahead depth).
    ``restart_drain`` lets quiesce revive a dead drain thread (the drain-thread-death
    recovery latch); turning it off makes thread death a hard :class:`ServeError`.
    """

    max_inflight: int = 64
    on_full: str = "block"
    queue_timeout_s: float = 30.0
    staging_slots: int = 2
    #: drain-side batch coalescing: when the window holds several consecutive batches of
    #: the same shape signature, the drain folds up to this many through ONE
    #: ``update_batches`` scan launch instead of one dispatch each — the structural
    #: throughput win a synchronous per-batch loop cannot have (k dispatches → 1,
    #: bit-identical by the tier-equivalence contract). 1 disables coalescing.
    coalesce: int = 16
    #: micro-batching dwell (milliseconds): with a short queue the drain waits up to
    #: this long for more same-shape batches before launching, so steady high-rate
    #: traffic coalesces instead of degenerating into per-batch launches that fight
    #: the enqueueing thread for the GIL (the Nagle tradeoff: + linger on commit
    #: latency, x coalesce on drain throughput). 0 launches immediately. Quiesce and
    #: close bypass the linger — a waiting reader never pays it.
    linger_ms: float = 0.0
    restart_drain: bool = True

    def __post_init__(self) -> None:
        if int(self.max_inflight) < 1:
            raise ServeError(f"ServeOptions(max_inflight) needs >= 1, got {self.max_inflight}")
        if int(self.coalesce) < 1:
            raise ServeError(f"ServeOptions(coalesce) needs >= 1, got {self.coalesce}")
        if float(self.linger_ms) < 0:
            raise ServeError(f"ServeOptions(linger_ms) needs >= 0, got {self.linger_ms}")
        if self.on_full not in _ON_FULL:
            raise ServeError(
                f"ServeOptions(on_full) must be one of {_ON_FULL}, got {self.on_full!r}"
            )
        if float(self.queue_timeout_s) < 0:
            raise ServeError(
                f"ServeOptions(queue_timeout_s) needs >= 0, got {self.queue_timeout_s}"
            )
        if int(self.staging_slots) < 1:
            raise ServeError(f"ServeOptions(staging_slots) needs >= 1, got {self.staging_slots}")


def serve_options_from_env() -> ServeOptions:
    """Build :class:`ServeOptions` from the ``TM_TPU_SERVE_*`` environment knobs."""

    def _f(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, default))
        except (TypeError, ValueError):
            return default

    on_full = str(os.environ.get(ENV_SERVE_ON_FULL, "block")).strip().lower()
    if on_full not in _ON_FULL:
        on_full = "block"
    return ServeOptions(
        max_inflight=int(_f(ENV_SERVE_MAX_INFLIGHT, 64)),
        on_full=on_full,
        queue_timeout_s=_f(ENV_SERVE_QUEUE_TIMEOUT, 30.0),
        staging_slots=int(_f(ENV_SERVE_STAGING_SLOTS, 2)),
        coalesce=int(_f(ENV_SERVE_COALESCE, 16)),
        linger_ms=_f(ENV_SERVE_LINGER, 0.0),
    )
