"""Serving-tier policy: the bounded in-flight window and its on-full semantics.

The bound is the robustness property: an unbounded enqueue path turns a traffic spike
into host-RAM/HBM exhaustion, a bounded one turns it into *backpressure* — the caller
blocks, errors, or sheds, and the engine's memory footprint stays ``O(max_inflight +
staging_slots)`` batches whatever the arrival rate does. ``on_full`` picks the contract:

==========  =========================================================================
``block``   park the caller with exponential-backoff waits until a slot frees; give up
            with :class:`~torchmetrics_tpu.utils.exceptions.BackpressureError` after
            ``queue_timeout_s`` (a stuck drain must not wedge the service forever)
``raise``   fail the enqueue immediately with :class:`BackpressureError` (the caller
            owns the retry/shed policy)
``shed``    drop the batch, count it (``serve.shed`` / ``robust.shed_batches``), warn
            once rank-zero, and return a ticket marked ``shed`` — graceful degradation
==========  =========================================================================

Env knobs (read by :func:`serve_options_from_env`, the default when ``update_async`` is
called on an unconfigured metric): ``TM_TPU_SERVE_MAX_INFLIGHT``, ``TM_TPU_SERVE_ON_FULL``,
``TM_TPU_SERVE_QUEUE_TIMEOUT_S``, ``TM_TPU_SERVE_STAGING_SLOTS``. A malformed or
out-of-range env value degrades to the field default with a ONE-SHOT rank-zero warning
(the warning cache dedups by message) — a typo'd deployment knob must not crash the
service at its first enqueue.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Optional, Type

from torchmetrics_tpu.utils.exceptions import ServeError
from torchmetrics_tpu.utils.prints import rank_zero_warn

ENV_SERVE_MAX_INFLIGHT = "TM_TPU_SERVE_MAX_INFLIGHT"
ENV_SERVE_ON_FULL = "TM_TPU_SERVE_ON_FULL"
ENV_SERVE_QUEUE_TIMEOUT = "TM_TPU_SERVE_QUEUE_TIMEOUT_S"
ENV_SERVE_STAGING_SLOTS = "TM_TPU_SERVE_STAGING_SLOTS"
ENV_SERVE_COALESCE = "TM_TPU_SERVE_COALESCE"
ENV_SERVE_LINGER = "TM_TPU_SERVE_LINGER_MS"

_ON_FULL = ("block", "raise", "shed")


@dataclass(frozen=True)
class ServeOptions:
    """Policy for one :class:`~torchmetrics_tpu.serve.engine.IngestEngine`.

    ``max_inflight`` bounds enqueued-but-uncommitted batches (the in-flight window,
    including the batch the drain thread is currently applying). ``queue_timeout_s``
    caps how long one blocking enqueue may park. ``staging_slots`` sizes the
    double-buffered host→device staging pipeline (transfer-ahead depth).
    ``restart_drain`` lets quiesce revive a dead drain thread (the drain-thread-death
    recovery latch); turning it off makes thread death a hard :class:`ServeError`.
    """

    max_inflight: int = 64
    on_full: str = "block"
    queue_timeout_s: float = 30.0
    staging_slots: int = 2
    #: drain-side batch coalescing: when the window holds several consecutive batches of
    #: the same shape signature, the drain folds up to this many through ONE
    #: ``update_batches`` scan launch instead of one dispatch each — the structural
    #: throughput win a synchronous per-batch loop cannot have (k dispatches → 1,
    #: bit-identical by the tier-equivalence contract). 1 disables coalescing.
    coalesce: int = 16
    #: micro-batching dwell (milliseconds): with a short queue the drain waits up to
    #: this long for more same-shape batches before launching, so steady high-rate
    #: traffic coalesces instead of degenerating into per-batch launches that fight
    #: the enqueueing thread for the GIL (the Nagle tradeoff: + linger on commit
    #: latency, x coalesce on drain throughput). 0 launches immediately. Quiesce and
    #: close bypass the linger — a waiting reader never pays it.
    linger_ms: float = 0.0
    restart_drain: bool = True

    def __post_init__(self) -> None:
        if int(self.max_inflight) < 1:
            raise ServeError(f"ServeOptions(max_inflight) needs >= 1, got {self.max_inflight}")
        if int(self.coalesce) < 1:
            raise ServeError(f"ServeOptions(coalesce) needs >= 1, got {self.coalesce}")
        if float(self.linger_ms) < 0:
            raise ServeError(f"ServeOptions(linger_ms) needs >= 0, got {self.linger_ms}")
        if self.on_full not in _ON_FULL:
            raise ServeError(
                f"ServeOptions(on_full) must be one of {_ON_FULL}, got {self.on_full!r}"
            )
        if float(self.queue_timeout_s) < 0:
            raise ServeError(
                f"ServeOptions(queue_timeout_s) needs >= 0, got {self.queue_timeout_s}"
            )
        if int(self.staging_slots) < 1:
            raise ServeError(f"ServeOptions(staging_slots) needs >= 1, got {self.staging_slots}")


def _env_num(name: str, default: Any, cast: Type,
             valid: Optional[Callable[[Any], bool]] = None) -> Any:
    """Read a numeric env knob; degrade to ``default`` on malformed/out-of-range values.

    The degradation warns rank-zero exactly once per (knob, bad value) — the warning
    cache dedups by message — so a typo'd ``TM_TPU_SERVE_*`` in a deployment manifest
    is loud in the logs but never crashes the service at its first enqueue.
    """
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        value = cast(float(raw)) if cast is int else cast(raw)
    except (TypeError, ValueError):
        rank_zero_warn(
            f"Ignoring malformed env {name}={raw!r} (not a {cast.__name__});"
            f" using the default {default!r}.",
            UserWarning,
        )
        return default
    if valid is not None and not valid(value):
        rank_zero_warn(
            f"Ignoring out-of-range env {name}={raw!r}; using the default {default!r}.",
            UserWarning,
        )
        return default
    return value


def serve_options_from_env() -> ServeOptions:
    """Build :class:`ServeOptions` from the ``TM_TPU_SERVE_*`` environment knobs.

    Malformed or out-of-range values degrade to the field defaults with a one-shot
    rank-zero warning per knob — they never raise.
    """
    on_full = str(os.environ.get(ENV_SERVE_ON_FULL, "block")).strip().lower()
    if on_full not in _ON_FULL:
        rank_zero_warn(
            f"Ignoring unknown env {ENV_SERVE_ON_FULL}={on_full!r} (valid: {_ON_FULL});"
            " using the default 'block'.",
            UserWarning,
        )
        on_full = "block"
    return ServeOptions(
        max_inflight=_env_num(ENV_SERVE_MAX_INFLIGHT, 64, int, lambda v: v >= 1),
        on_full=on_full,
        queue_timeout_s=_env_num(ENV_SERVE_QUEUE_TIMEOUT, 30.0, float, lambda v: v >= 0),
        staging_slots=_env_num(ENV_SERVE_STAGING_SLOTS, 2, int, lambda v: v >= 1),
        coalesce=_env_num(ENV_SERVE_COALESCE, 16, int, lambda v: v >= 1),
        linger_ms=_env_num(ENV_SERVE_LINGER, 0.0, float, lambda v: v >= 0),
    )
