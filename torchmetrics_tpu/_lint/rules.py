"""jaxlint rule registry: the TPU hazard rules over a shared per-module inference pass.

All rules consume one :class:`_ModuleModel` built per file:

- **jit-context detection** — which functions execute under ``jax.jit`` tracing. Roots are
  (1) ``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` decorators, (2) functions referenced
  inside a ``jax.jit`` / ``vmap`` / ``lax.scan`` / ``lax.cond`` /… wrapper call, and (3) this
  repo's engine convention: ``_update`` / ``_compute`` / ``_metric_kernel`` / ``_flat_values``
  methods are jitted by ``Metric`` unless the class sets ``jit_update``/``jit_compute`` to
  False. Context propagates through the intra-module call graph (plain calls and
  ``self.method`` calls) and into nested helper defs.
- **traced-name dataflow** — per function, which local names hold (possibly) device/traced
  array values: non-static parameters of jit functions, plus anything assigned from a
  ``jnp.*`` / ``lax.*`` / ``jax.*`` device-producing call or from calling a locally
  ``jax.jit``-wrapped callable. Parameters declared in ``static_argnames`` and parameters
  with constant (str/bool/number) defaults are static; free (closure) variables are assumed
  static — under-reporting beats drowning real findings in noise.

The rules (documented with examples in ``docs/static-analysis.md``):

========  ======================================================================
TPU001    host-sync coercion: ``.item()`` / ``float()`` / ``int()`` / ``bool()``
          on a device value — blocking D2H sync eagerly, trace error under jit
TPU002    data-dependent Python ``if``/``while`` on a traced value inside jit
TPU003    host ``numpy`` op applied to a traced value inside jit
TPU004    jit wrap leaving str/bool config parameters non-static (retrace churn)
TPU005    ``add_state`` reduction/dtype mismatch (overflow, non-additive sum)
TPU006    fresh ``jnp`` constant built inside a per-step hot path (re-upload)
TPU007    value read after being donated to a compiled dispatch (deleted buffer)
TPU008    bare ``assert`` on a traced value inside jit (a validation no-op)
TPU009    telemetry/``obs`` registry call inside a jit-traced function (the host
          side effect runs at trace time only — silently dropped per step)
TPU010    host-side Python loop calling ``.update()``/``.forward()`` over a
          dict/list of Metric instances (per-key loop — use KeyedMetric)
TPU011    full-state allgather (``gather_all_arrays``/``process_allgather``/…)
          on a metric that declared a sharded spec (re-replicates every shard)
TPU012    donation-lifetime race: a donated buffer (or a sibling alias of one)
          is read after dispatch and before the commit/recover seam
TPU013    sharding consistency: hand-mutation of ``.shard()``-placed state
          without ``with_sharding_constraint``, or a shard-order-dependent
          float fold over gathered/cat state
TPU014    unbounded ``add_state(default=[], dist_reduce_fx="cat")`` on a
          metric with a registered streaming-sketch equivalent and no
          ``approx="sketch"`` wiring (state grows with samples seen)
TPU015    host-blocking call (``.block_until_ready()`` / ``jax.device_get`` /
          ``.item()``/``.tolist()``) reachable from an async serve/drain path
          (a ``serve/`` module or a ``# jaxlint: serve-path`` function)
TPU017    wall-clock read (``time.time()``/``time.monotonic()``/
          ``datetime.now()``) inside jit-traced code or a per-step hot path
          (non-reproducible boundaries + trace-time freeze)
TPU018    lossy sync compression (``SyncOptions(compression="bf16"|"int8")``)
          configured next to a metric state whose callable ``dist_reduce_fx``
          carries no traceable/merge contract (not error-feedback safe)
TPU020    process-identity read (``os.getpid()``/``socket.gethostname()``/
          ``uuid``/``process_fingerprint``) inside jit-traced code — the
          identity is frozen at trace time, stale after restart/cache hit
TPU025    ``jit`` applied to a lambda or a locally-def'd closure inside a
          function body — a fresh wrapper per call defeats the compilation
          cache (silent retrace-every-call; the compile plane flags the churn)
========  ======================================================================

**Interprocedural marks** (set by :mod:`torchmetrics_tpu._lint.project`, never by the
per-module pass): a :class:`_FuncInfo` can carry ``via`` (the cross-module call path that
put it in jit context), ``extra_traced`` (parameters that receive device values at some
call site), ``hot``/``hot_via`` (reached from an eager per-step entry point), and
``donating_params`` (parameters bound to donating callables at call sites). Rules consume
the marks exactly like locally-inferred facts, and append the ``via:`` call path to their
messages — a per-module run (``analyze_source``) has no marks, so its behaviour is
unchanged; the whole-program run is strictly more informed.
"""
from __future__ import annotations

import ast
import re
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from torchmetrics_tpu._lint.core import Finding

#: rule id -> metadata record driving ``--list-rules``, the SARIF export, and the
#: generated catalog table in ``docs/static-analysis.md`` (``_lint/catalog.py``; the
#: doc-sync test fails when the table drifts from this registry). Severities: ``error``
#: = wrong results or a crash, ``warning`` = silently-degraded semantics, ``perf`` =
#: correct but measurably slower.
RULE_META: Dict[str, Dict[str, str]] = {
    "TPU000": {
        "severity": "error",
        "summary": "file does not parse (analyzer cannot run)",
        "example": "def f(:",
        "fix": "fix the syntax error; every other rule is blind until the file parses",
    },
    "TPU001": {
        "severity": "perf",
        "summary": "host-sync coercion (.item()/float()/int()/bool()) on a device array value",
        "example": "return float(jnp.mean(x))",
        "fix": "read once via jax.device_get(...) — the sync stays, but explicit and counted",
    },
    "TPU002": {
        "severity": "error",
        "summary": "data-dependent Python if/while on a traced array inside jit",
        "example": "if x.sum() > 0: ...",
        "fix": "lower the branch into the program (jnp.where / lax.cond) or declare the"
               " driver in static_argnames",
    },
    "TPU003": {
        "severity": "error",
        "summary": "host numpy op applied to a traced value inside jit",
        "example": "np.log(x)  # x traced",
        "fix": "use the jnp equivalent, or hoist the op out of the traced region",
    },
    "TPU004": {
        "severity": "perf",
        "summary": "jit call-site leaves config parameters non-static (retrace churn)",
        "example": "jax.jit(kernel)  # kernel(x, mode='fast')",
        "fix": "declare str/bool config parameters in static_argnames",
    },
    "TPU005": {
        "severity": "error",
        "summary": "add_state reduction/dtype mismatch (overflow or non-additive update)",
        "example": "self.add_state('count', jnp.asarray(0), dist_reduce_fx='sum')",
        "fix": "zero defaults + wide dtypes for sums, ±inf identities for min/max,"
               " accumulate (never assign) sum-reduced states",
    },
    "TPU006": {
        "severity": "perf",
        "summary": "fresh jnp constant built inside a per-step hot path (constant re-upload)",
        "example": "def forward(self, x): return x + jnp.zeros((4,))",
        "fix": "hoist the constant to a module/instance-level value built once",
    },
    "TPU007": {
        "severity": "error",
        "summary": "value read after being donated to a compiled dispatch (deleted buffer)",
        "example": "out = step(state, b); state.sum()",
        "fix": "rebind the name to the dispatch output, or drop donate_argnums for it",
    },
    "TPU008": {
        "severity": "warning",
        "summary": "bare assert on a traced value inside jit (compiled away - a validation no-op)",
        "example": "assert jnp.all(x >= 0)",
        "fix": "hoist the check to the eager host path, or fold it into the graph"
               " (nan_policy / a counted guard state)",
    },
    "TPU009": {
        "severity": "warning",
        "summary": "telemetry/obs registry call inside jit-traced code (runs at trace time only)",
        "example": "obs.bump(self, 'calls')  # inside _update",
        "fix": "instrument the eager caller; fold per-step quantities into the program"
               " as a state output",
    },
    "TPU010": {
        "severity": "perf",
        "summary": "host-side per-key Metric update loop (one dispatch per key - use KeyedMetric)",
        "example": "for uid, m in per_user.items(): m.update(v[uid])",
        "fix": "route the mixed-key batch through keyed.KeyedMetric(template, num_keys=N)",
    },
    "TPU011": {
        "severity": "perf",
        "summary": "full-state allgather on sharded metric state (re-replicates every shard)",
        "example": "gather_all_arrays(km.metric_state['v'])  # km.shard()-ed",
        "fix": "let compute()/process_sync drive the reduce-scatter sharded sync",
    },
    "TPU012": {
        "severity": "error",
        "summary": "donation-lifetime race: donated buffer (or sibling alias) read before re-commit",
        "example": "alias = state; out = step(state, b); alias.sum()",
        "fix": "read only after the commit/recover seam (commit_step / commit_donated),"
               " and never through a pre-donation alias",
    },
    "TPU013": {
        "severity": "error",
        "summary": "sharded-state consistency: hand mutation without with_sharding_constraint,"
                   " or shard-order-dependent float fold",
        "example": "m.shard(mesh); m.metric_state['v'] = jnp.zeros_like(v)",
        "fix": "mutate through the engine's kernels (closed under sharding constraints);"
               " make cross-shard float folds order-fixed before reducing",
    },
    "TPU014": {
        "severity": "perf",
        "summary": "unbounded cat state on a metric with a registered sketch equivalent"
                   " (state/snapshot/sync bytes grow with samples seen)",
        "example": "self.add_state('preds', [], dist_reduce_fx='cat')  # curve metric",
        "fix": "offer (or use) the O(1) streaming sketch twin — approx='sketch' with the"
               " documented error bound (docs/sketches.md)",
    },
    "TPU015": {
        "severity": "perf",
        "summary": "host-blocking call (.block_until_ready()/.item()/.tolist()/device_get)"
                   " reachable from an async serve/drain path (stalls the ingestion pipeline)",
        "example": "def _drain(self): jax.device_get(out)  # under serve/",
        "fix": "keep the drain non-blocking: dispatch and commit device futures; read"
               " values only after quiesce (compute()/snapshot() quiesce for you)",
    },
    "TPU016": {
        "severity": "warning",
        "summary": "span begun without with/try-finally closure (leaks an open slice),"
                   " or trace-ring/series mutation inside jit-traced code",
        "example": "s = telemetry.span('x'); s.__enter__()",
        "fix": "enter spans via `with` (or try/finally calling __exit__); emit trace"
               " stage events and series records from the eager host path only",
    },
    "TPU017": {
        "severity": "warning",
        "summary": "wall-clock read (time.time/time.monotonic/datetime.now) in jit-traced"
                   " code or a per-step hot path (irreproducible boundaries, frozen under trace)",
        "example": "if time.time() - start > 60: self.advance()",
        "fix": "gate logic on a step/update COUNT (deterministic, journal-replayable);"
               " pass timestamps in as inputs; time.perf_counter stays fine for"
               " pure measurement that never feeds control flow",
    },
    "TPU018": {
        "severity": "warning",
        "summary": "lossy sync compression configured beside a callable dist_reduce_fx"
                   " without a traceable/merge contract (not error-feedback safe)",
        "example": "self.add_state('v', init, dist_reduce_fx=my_fold)\n"
                   "SyncOptions(compression='int8')",
        "fix": "mark the reducer's merge contract (fx.traceable = True — a mergeable"
               " fold over stacked states, exact on decoded wire values), register the"
               " state as a sketch (packed lossless wire), or keep compression='none'"
               " for this metric",
    },
    "TPU019": {
        "severity": "warning",
        "summary": "broad except that swallows silently (no re-raise, no telemetry/"
                   "flight-ring record, no fallback return) on a serve/sync/robust seam",
        "example": "def drain(self):\n    try: apply(batch)\n    except Exception: pass",
        "fix": "re-raise, return an explicit degraded value, or record the absorption"
               " (telemetry counter / obs.flightrec.record / rank_zero_warn) — a"
               " swallowed failure on a recovery seam is an observability kill",
    },
    "TPU020": {
        "severity": "warning",
        "summary": "process-identity read (os.getpid/socket.gethostname/uuid/"
                   "process_fingerprint) inside jit-traced code — frozen at trace time,"
                   " stale after restart or a compilation-cache hit",
        "example": "label = f\"{socket.gethostname()}:{os.getpid()}\"  # inside jit",
        "fix": "read identity once on the eager host path (obs.process_fingerprint())"
               " and attach it as labels/metadata outside the traced computation —"
               " never bake who-am-I into a compiled program",
    },
    "TPU021": {
        "severity": "error",
        "summary": "shared attribute/global written from ≥2 concurrent thread roots"
                   " with disjoint locksets (lost update); GIL-atomic ring appends and"
                   " declared '# jaxlint: single-mutator' fields are sanctioned",
        "example": "def _drain_loop(self):  # Thread(target=...) root\n"
                   "    self._stats['failed'] += n  # main root writes under self._cond",
        "fix": "take the same lock at every write site, or — when the design is a"
               " single-mutator protocol (quiesce barrier, sole-writer thread) — mark"
               " the site '# jaxlint: single-mutator (racerun: <scenario>)' and back it"
               " with a passing deterministic schedule (make jaxlint-race)",
    },
    "TPU022": {
        "severity": "error",
        "summary": "public host-access entry point of an engine-attachable class"
                   " (assigns self._serve) touches tensor state without routing through"
                   " the quiesce seam — the docs/serving.md table, checked structurally",
        "example": "def peek(self):\n    return dict(self._state.tensors)  # no quiesce",
        "fix": "drain the async window first: call self._serve.quiesce() (directly or"
               " via a same-class helper that does) before reading/writing tensor state,"
               " exactly like compute()/sync()/state_dict() do",
    },
    "TPU023": {
        "severity": "warning",
        "summary": "check-then-act (if/while test) or multi-step read (iteration) of a"
                   " shared field outside the lock that guards its concurrent writers",
        "example": "if self._closed:  # close() flips _closed under self._lock\n"
                   "    return",
        "fix": "hold the writers' guard across the whole check-then-act region (or the"
               " whole iteration); a decision taken on an unlocked read races the"
               " concurrent writer even though the single load itself is GIL-atomic",
    },
    "TPU024": {
        "severity": "warning",
        "summary": "actuator state transition (admission mode / linger / coalesce /"
                   " dwell store) in a serve/robust seam function with no"
                   " flight-recorder emission in the same function",
        "example": "def _escalate(self, ch):\n"
                   "    ch.mode_idx += 1  # no flightrec.record in this function",
        "fix": "funnel every actuator mutation through one seam that both moves the"
               " state AND records it (flightrec.record('control.decision', ...) or"
               " open_incident) with the triggering signal values — the decision"
               " journal, replay bit-identity, and post-mortem bundles all assume the"
               " control event stream is complete (docs/serving.md 'Control loop')",
    },
    "TPU025": {
        "severity": "warning",
        "summary": "jit of a lambda or locally-def'd closure immediately invoked or"
                   " rebuilt inside a loop — the wrapper (and its compilation cache) is"
                   " rebuilt on every call, so the kernel silently retraces per"
                   " invocation",
        "example": "def step(self, x):\n    return jax.jit(lambda s: s + x)(self.s)",
        "fix": "hoist the jitted function to module/class scope, or cache the wrapper"
               " once (the engine's _jit_cache pattern) so repeat calls hit the same"
               " compiled program — obs.xplane's compile ledger will show the churn"
               " this rule catches statically",
    },
}

#: rule id -> one-line description (derived view of :data:`RULE_META`; kept for the CLI,
#: the SARIF export, and callers that predate the metadata registry).
RULES: Dict[str, str] = {rid: meta["summary"] for rid, meta in RULE_META.items()}

# wrapper callables whose function arguments execute under tracing
_TRACE_WRAPPERS = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "scan", "cond", "while_loop", "fori_loop", "switch", "associated_scan", "map",
    "shard_map", "custom_jvp", "custom_vjp", "filter_jit",
}
# attribute accesses that yield static (trace-time) metadata, never a traced value
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}
# jnp/lax attributes that return host/static values, not device arrays
_HOST_FINAL = {"shape", "ndim", "size", "result_type", "dtype", "iinfo", "finfo", "issubdtype"}
# jax.* attributes that return host values or callables (not device arrays)
_JAX_HOST_FINAL = {
    "device_get", "block_until_ready", "jit", "vmap", "pmap", "grad", "value_and_grad",
    "checkpoint", "remat", "process_count", "process_index", "device_count",
    "local_device_count", "devices", "local_devices", "default_backend", "tree_map",
    "tree_leaves", "tree_flatten", "tree_unflatten", "named_scope", "eval_shape",
}
# host-side predicates/introspection whose results are static w.r.t. tracing
_STATIC_CALLS = {"len", "isinstance", "callable", "hasattr", "getattr", "type", "is_traced"}
# engine-convention methods jitted by the Metric shell (see metric.py _jitted_update/_compute)
_CONVENTION_JIT = {"_update": "jit_update", "_compute": "jit_compute",
                   "_metric_kernel": None, "_flat_values": None}
# eager per-step entry points for TPU006 (the engine calls these once per batch)
_HOT_PREFIXES = ("update", "forward", "_forward", "_update_")
_HOT_EXACT = {"update", "forward", "__call__"}
# jnp constructors whose all-constant calls re-upload a host constant every execution
_CONST_BUILDERS = {"array", "asarray", "zeros", "ones", "full", "arange", "eye", "linspace"}


def _dotted(node: ast.AST) -> Optional[List[str]]:
    """``a.b.c`` → ["a", "b", "c"]; None for anything that is not a pure name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _final_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _scoped_walk(root: ast.AST) -> Iterator[ast.AST]:
    """Walk ``root``'s subtree without descending into nested function/class scopes."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _const_value(node: ast.AST) -> Any:
    """Python value of a (possibly negated) literal; ``_NOT_CONST`` sentinel otherwise."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_value(node.operand)
        if isinstance(inner, (int, float)):
            return -inner
    return _NOT_CONST


_NOT_CONST = object()


class _FuncInfo:
    __slots__ = (
        "node", "name", "parent", "cls", "jit", "jit_root", "static_params", "children",
        # interprocedural marks — empty/None after the per-module pass; populated only by
        # the whole-program pass (project.py), consumed by the rules below
        "via", "extra_traced", "hot", "hot_via", "donating_params",
    )

    def __init__(self, node, name, parent, cls):
        self.node = node
        self.name = name
        self.parent: Optional["_FuncInfo"] = parent
        self.cls: Optional[str] = cls
        self.jit = False
        #: True when jit context is intrinsic (decorator / wrapper ref / engine
        #: convention) — every non-static parameter is traced. Propagated callees
        #: (jit=True, jit_root=False) trace only the parameters observed to receive
        #: device values at call sites (``extra_traced``): a helper's host-config
        #: arguments stay static even though the helper runs under the caller's trace.
        self.jit_root = False
        self.static_params: Set[str] = set()
        self.children: List["_FuncInfo"] = []
        #: cross-module call path that put this function in jit context, e.g.
        #: ("metric.py::Metric._update", "helpers.py::fold") — None when jit was local
        self.via: Optional[Tuple[str, ...]] = None
        #: parameter names that receive a device/traced value at some call site
        self.extra_traced: Set[str] = set()
        #: reached (transitively) from an eager per-step entry point
        self.hot = False
        self.hot_via: Optional[Tuple[str, ...]] = None
        #: parameter name -> donated positions, for donating callables received as args
        self.donating_params: Dict[str, Set[int]] = {}

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


def _via_suffix(via: Optional[Tuple[str, ...]]) -> str:
    """Render an interprocedural call path for a finding message ('' per-module)."""
    if not via:
        return ""
    return f" [via: {' -> '.join(via)}]"


class _ModuleModel:
    """Per-file inference shared by every rule: functions, classes, jit context, call graph.

    ``extra_flags_off`` injects class-level ``jit_update``/``jit_compute`` opt-outs the
    per-module pass cannot see (flags inherited from bases defined in OTHER modules) —
    the project pass resolves those and rebuilds the model with them, so convention-jit
    marking honors the true runtime contract.
    """

    def __init__(
        self, tree: ast.Module, extra_flags_off: Optional[Dict[str, Set[str]]] = None
    ) -> None:
        self.tree = tree
        self.functions: List[_FuncInfo] = []
        self.by_name: Dict[str, List[_FuncInfo]] = {}
        self.class_nodes: Dict[str, ast.ClassDef] = {}
        self.class_flags_off: Dict[str, Set[str]] = {}  # class -> {"jit_update", ...} set False
        self._extra_flags_off = extra_flags_off or {}
        self._dead_spans: Dict[int, List[Tuple[int, int]]] = {}
        self._collect(tree, parent=None, cls=None)
        self._detect_class_flags()
        self._mark_jit_roots()
        self._propagate_jit()

    # ---------------------------------------------------------------- model construction
    def _collect(self, node: ast.AST, parent: Optional[_FuncInfo], cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FuncInfo(child, child.name, parent, cls)
                self.functions.append(info)
                self.by_name.setdefault(child.name, []).append(info)
                if parent is not None:
                    parent.children.append(info)
                self._collect(child, parent=info, cls=cls)
            elif isinstance(child, ast.ClassDef):
                self.class_nodes[child.name] = child
                self._collect(child, parent=None, cls=child.name)
            else:
                self._collect(child, parent=parent, cls=cls)

    def _detect_class_flags(self) -> None:
        """Find ``jit_update = False`` / ``self.jit_compute = False`` per class.

        Flags inherit through base classes defined in the same module (cross-module bases
        are invisible to a per-file pass — classes relying on an imported base's flag can
        restate it as a class attribute to make the intent statically checkable).
        """
        for cname, cnode in self.class_nodes.items():
            off: Set[str] = set()
            for node in ast.walk(cnode):
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                else:
                    continue
                if not (isinstance(value, ast.Constant) and value.value is False):
                    continue
                for t in targets:
                    name = None
                    if isinstance(t, ast.Name):
                        name = t.id
                    elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) and t.value.id == "self":
                        name = t.attr
                    if name in ("jit_update", "jit_compute"):
                        off.add(name)
            self.class_flags_off[cname] = off | self._extra_flags_off.get(cname, set())
        # one inheritance sweep per depth level (module class chains are shallow)
        for _ in range(len(self.class_nodes)):
            changed = False
            for cname, cnode in self.class_nodes.items():
                for base in cnode.bases:
                    bname = _final_name(base)
                    if bname in self.class_flags_off:
                        merged = self.class_flags_off[cname] | self.class_flags_off[bname]
                        if merged != self.class_flags_off[cname]:
                            self.class_flags_off[cname] = merged
                            changed = True
            if not changed:
                break

    def _resolve_refs(self, call: ast.Call) -> List[_FuncInfo]:
        """Local function defs referenced (by name or ``self.attr``) inside a wrapper call.

        Only the call's ARGUMENTS are searched — the callee expression itself is not a
        reference (``self.checkpoint(...)`` calls a method that happens to share a
        wrapper's name; it does not hand it to a tracer).
        """
        refs: List[_FuncInfo] = []
        for root in [*call.args, *(kw.value for kw in call.keywords)]:
            for sub in ast.walk(root):
                if isinstance(sub, ast.Name) and sub.id in self.by_name:
                    refs.extend(self.by_name[sub.id])
                elif (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                    and sub.attr in self.by_name
                ):
                    refs.extend(fi for fi in self.by_name[sub.attr] if fi.cls is not None)
        return refs

    @staticmethod
    def _statics_from_keywords(keywords: Sequence[ast.keyword]) -> Set[str]:
        names: Set[str] = set()
        for kw in keywords:
            if kw.arg == "static_argnames":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    names.add(v.value)
                elif isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                    for el in v.elts:
                        if isinstance(el, ast.Constant) and isinstance(el.value, str):
                            names.add(el.value)
        return names

    @staticmethod
    def _static_nums_from_keywords(keywords: Sequence[ast.keyword]) -> Set[int]:
        nums: Set[int] = set()
        for kw in keywords:
            if kw.arg == "static_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    nums.add(v.value)
                elif isinstance(v, (ast.Tuple, ast.List)):
                    for el in v.elts:
                        if isinstance(el, ast.Constant) and isinstance(el.value, int):
                            nums.add(el.value)
        return nums

    def _jit_wrap_of_decorator(self, dec: ast.AST) -> Optional[Tuple[Set[str], Set[int]]]:
        """(static_argnames, static_argnums) when ``dec`` is a jit-ish decorator, else None."""
        if _final_name(dec) in ("jit", "pjit", "filter_jit"):
            return set(), set()
        if isinstance(dec, ast.Call):
            fn = _final_name(dec.func)
            if fn in ("jit", "pjit", "filter_jit"):
                return self._statics_from_keywords(dec.keywords), self._static_nums_from_keywords(dec.keywords)
            if fn == "partial" and dec.args and _final_name(dec.args[0]) in ("jit", "pjit"):
                return self._statics_from_keywords(dec.keywords), self._static_nums_from_keywords(dec.keywords)
        return None

    def _mark_jit_roots(self) -> None:
        # (1) decorator roots
        for info in self.functions:
            for dec in info.node.decorator_list:
                wrap = self._jit_wrap_of_decorator(dec)
                if wrap is not None:
                    info.jit = info.jit_root = True
                    info.static_params |= wrap[0]
                    info.static_params |= self._argnums_to_names(info.node, wrap[1])
        # (2) wrapper-call roots: jax.jit(f, ...), jax.vmap(f), lax.scan(body, ...), ...
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _final_name(node.func)
            if fn not in _TRACE_WRAPPERS:
                continue
            statics = self._statics_from_keywords(node.keywords) if fn in ("jit", "pjit") else set()
            for ref in self._resolve_refs(node):
                ref.jit = ref.jit_root = True
                ref.static_params |= statics
        # (3) engine-convention roots (Metric shell jits these)
        for info in self.functions:
            if info.cls is None or info.name not in _CONVENTION_JIT:
                continue
            flag = _CONVENTION_JIT[info.name]
            if flag is not None and flag in self.class_flags_off.get(info.cls, set()):
                continue
            info.jit = info.jit_root = True

    @staticmethod
    def _argnums_to_names(node: ast.AST, nums: Set[int]) -> Set[str]:
        params = [a.arg for a in node.args.posonlyargs + node.args.args]
        return {params[i] for i in nums if 0 <= i < len(params)}

    def _propagate_jit(self) -> None:
        """Flow jit context through plain / ``self.method`` calls and into nested defs.

        Callees gain jit context WITHOUT becoming roots: the traced seed of a propagated
        callee is the set of parameters that receive a device expression at some call
        site (bound here positionally and by keyword), so a helper's host-config
        arguments stay static under the caller's trace.
        """
        changed = True
        while changed:
            changed = False
            for info in self.functions:
                if not info.jit:
                    continue
                traced, jit_callables = self.traced_names(info)
                guard_spans = self.config_guard_spans(info)
                for child in info.children:
                    if not child.jit:
                        child.jit = True
                        changed = True
                for node in _scoped_walk(info.node):
                    if not isinstance(node, ast.Call):
                        continue
                    if any(lo <= node.lineno <= hi for lo, hi in guard_spans):
                        continue  # eager-by-contract (config-gated) call site
                    callees: List[_FuncInfo] = []
                    if isinstance(node.func, ast.Name) and node.func.id in self.by_name:
                        callees = [fi for fi in self.by_name[node.func.id] if fi.cls is None or fi.cls == info.cls]
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in self.by_name
                    ):
                        callees = [fi for fi in self.by_name[node.func.attr] if fi.cls == info.cls]
                    for callee in callees:
                        if callee is info:
                            continue
                        if not callee.jit:
                            callee.jit = True
                            changed = True
                        if self._bind_call_args(node, callee, traced, jit_callables):
                            changed = True

    @staticmethod
    def _bind_call_args(
        call: ast.Call, callee: "_FuncInfo", traced: Set[str], jit_callables: Set[str]
    ) -> bool:
        """Mark callee parameters bound to device expressions at this call site."""
        args = callee.node.args
        params = [a.arg for a in args.posonlyargs + args.args if a.arg not in ("self", "cls")]
        kwonly = {a.arg for a in args.kwonlyargs}
        changed = False
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred) or i >= len(params):
                continue
            p = params[i]
            if p in callee.extra_traced or p in callee.static_params:
                continue
            if _is_device_expr(arg, traced, jit_callables):
                callee.extra_traced.add(p)
                changed = True
        for kw in call.keywords:
            if kw.arg is None or (kw.arg not in params and kw.arg not in kwonly):
                continue
            if kw.arg in callee.extra_traced or kw.arg in callee.static_params:
                continue
            if _is_device_expr(kw.value, traced, jit_callables):
                callee.extra_traced.add(kw.arg)
                changed = True
        return changed

    # ------------------------------------------------------------------- per-function facts
    def traced_names(self, info: _FuncInfo) -> Tuple[Set[str], Set[str]]:
        """(traced value names, locally-jitted callable names) for one function body.

        Traced seeds: in a jit ROOT (decorator / wrapper ref / engine convention), every
        parameter that is not ``self``/``cls``, not in ``static_argnames``, and has no
        constant (str/bool/number) default. A propagated-jit callee (reached from a root
        through the call graph) traces only the parameters observed to receive device
        values at call sites (``extra_traced``) — its host-config arguments stay static.
        In eager context parameters are NOT assumed traced — only dataflow from
        device-producing calls is.
        """
        traced: Set[str] = set()
        jit_callables: Set[str] = set()
        args = info.node.args
        if info.jit and info.jit_root:
            params = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
            if args.vararg:
                params.append(args.vararg.arg)
            if args.kwarg:
                params.append(args.kwarg.arg)
            defaulted: Set[str] = set()
            pos = args.posonlyargs + args.args
            for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
                if _const_value(d) is not _NOT_CONST:
                    defaulted.add(a.arg)
            for a, d in zip(args.kwonlyargs, args.kw_defaults):
                if d is not None and _const_value(d) is not _NOT_CONST:
                    defaulted.add(a.arg)
            traced = {
                p for p in params
                if p not in ("self", "cls") and p not in info.static_params and p not in defaulted
            }
        # interprocedural mark: parameters observed to receive device values at call
        # sites (project pass) seed the dataflow even in eager context
        traced |= info.extra_traced
        # dataflow fixpoint over assignments (source order is irrelevant to the fixpoint)
        assigns: List[Tuple[List[ast.AST], ast.AST]] = []
        for node in _scoped_walk(info.node):
            if isinstance(node, ast.Assign):
                assigns.append((list(node.targets), node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                assigns.append(([node.target], node.value))
            elif isinstance(node, ast.AugAssign):
                assigns.append(([node.target], node.value))
            elif isinstance(node, ast.For):
                assigns.append(([node.target], node.iter))
        for _ in range(4):  # small fixpoint: chains deeper than 4 hops are vanishingly rare
            changed = False
            for targets, value in assigns:
                if isinstance(value, ast.Call) and _final_name(value.func) in ("jit", "pjit"):
                    for name in self._target_names(targets):
                        if name not in jit_callables:
                            jit_callables.add(name)
                            changed = True
                    continue
                if _is_device_expr(value, traced, jit_callables):
                    for name in self._target_names(targets):
                        if name not in traced:
                            traced.add(name)
                            changed = True
            if not changed:
                break
        return traced, jit_callables

    # -------------------------------------------------------------------- trace-dead code
    def trace_dead_spans(self, info: _FuncInfo) -> List[Tuple[int, int]]:
        """Line spans of ``info`` that can NEVER execute under jax tracing.

        The repo's sanctioned eager-only idioms, modeled so jit-context rules do not
        flag code the trace provably skips:

        - ``if is_traced(...): return`` as a function-body statement — everything after
          the guard is eager-only (the tracer returns at the top);
        - the body of any ``if`` whose test contains a ``not is_traced(...)`` conjunct —
          under trace the guard short-circuits False before the body runs;
        - operands FOLLOWING ``not is_traced(x)`` inside an ``and`` chain — Python's
          short-circuit means they only evaluate eagerly (``not is_traced(x) and
          float(x) < 2`` never coerces a tracer).
        """
        cached = self._dead_spans.get(id(info))
        if cached is not None:
            return cached
        spans: List[Tuple[int, int]] = []
        fn_end = getattr(info.node, "end_lineno", None) or info.node.lineno
        for i, stmt in enumerate(info.node.body):
            if (
                isinstance(stmt, ast.If)
                and isinstance(stmt.test, ast.Call)
                and _final_name(stmt.test.func) == "is_traced"
                and any(isinstance(s, ast.Return) for s in stmt.body)
            ):
                start = (getattr(stmt, "end_lineno", None) or stmt.lineno) + 1
                if start <= fn_end:
                    spans.append((start, fn_end))
                break
        for node in _scoped_walk(info.node):
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
                guarded = _is_trace_guard(test) or (
                    isinstance(test, ast.BoolOp)
                    and isinstance(test.op, ast.And)
                    and any(_is_trace_guard(v) for v in test.values)
                )
                if guarded and node.body:
                    spans.append((
                        node.body[0].lineno,
                        getattr(node.body[-1], "end_lineno", None) or node.body[-1].lineno,
                    ))
            if isinstance(node, ast.BoolOp) and isinstance(node.op, ast.And):
                for i, v in enumerate(node.values):
                    if _is_trace_guard(v) and i + 1 < len(node.values):
                        tail = node.values[i + 1:]
                        spans.append((
                            tail[0].lineno,
                            getattr(tail[-1], "end_lineno", None) or tail[-1].lineno,
                        ))
                        break
        self._dead_spans[id(info)] = spans
        return spans

    def is_trace_dead(self, info: _FuncInfo, node: ast.AST) -> bool:
        line = getattr(node, "lineno", 0)
        return any(lo <= line <= hi for lo, hi in self.trace_dead_spans(info))

    def config_guard_spans(self, info: _FuncInfo) -> List[Tuple[int, int]]:
        """Spans of ``if <bool config param>:`` bodies — eager-by-contract call sites.

        The repo's functional APIs gate validation behind ``validate_args: bool = True``;
        a jit caller disables it (``jax.jit(lambda p, t: f(p, t, validate_args=False))``),
        so jit context must NOT propagate into calls under such a guard: the guarded
        helpers run eagerly or not at all. Only a bare boolean-defaulted/annotated
        parameter (or its negation) counts — data-dependent tests never match.
        """
        bool_params: Set[str] = set()
        args = info.node.args
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            if isinstance(_const_value(d), bool):
                bool_params.add(a.arg)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and isinstance(_const_value(d), bool):
                bool_params.add(a.arg)
        for a in pos + args.kwonlyargs:
            if a.annotation is not None and _final_name(a.annotation) == "bool":
                bool_params.add(a.arg)
        if not bool_params:
            return []
        spans: List[Tuple[int, int]] = []
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.If) or not node.body:
                continue
            test = node.test
            if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
                test = test.operand
            if isinstance(test, ast.Name) and test.id in bool_params:
                spans.append((
                    node.body[0].lineno,
                    getattr(node.body[-1], "end_lineno", None) or node.body[-1].lineno,
                ))
        return spans

    @staticmethod
    def _target_names(targets: Sequence[ast.AST]) -> Iterator[str]:
        for t in targets:
            if isinstance(t, ast.Name):
                yield t.id
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    if isinstance(el, ast.Name):
                        yield el.id
                    elif isinstance(el, ast.Starred) and isinstance(el.value, ast.Name):
                        yield el.value.id


def _is_device_expr(node: ast.AST, traced: Set[str], jit_callables: Set[str]) -> bool:
    """Could this expression evaluate to a device array / tracer?"""
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute):
        if node.attr in _STATIC_ATTRS:
            return False
        return _is_device_expr(node.value, traced, jit_callables)
    if isinstance(node, ast.Subscript):
        return _is_device_expr(node.value, traced, jit_callables)
    if isinstance(node, ast.Call):
        fn = node.func
        dotted = _dotted(fn)
        if dotted is not None:
            root, final = dotted[0], dotted[-1]
            if root in ("jnp", "lax") and final not in _HOST_FINAL:
                return True
            if root == "jax" and len(dotted) > 1 and dotted[1] == "numpy" and final not in _HOST_FINAL:
                return True
            if root == "jax" and final not in _JAX_HOST_FINAL and final not in _HOST_FINAL:
                return True
            if root in ("np", "numpy", "math"):
                return False
            if final in _STATIC_CALLS:
                return False
        if isinstance(fn, ast.Name) and fn.id in jit_callables:
            return True
        if isinstance(fn, ast.Attribute):
            # method call on a traced value (x.astype(...), x.at[...].set(...), x.sum())
            return _is_device_expr(fn.value, traced, jit_callables)
        return False
    if isinstance(node, (ast.BinOp,)):
        return any(_is_device_expr(c, traced, jit_callables) for c in (node.left, node.right))
    if isinstance(node, ast.UnaryOp):
        return _is_device_expr(node.operand, traced, jit_callables)
    if isinstance(node, ast.Compare):
        return any(_is_device_expr(c, traced, jit_callables) for c in [node.left, *node.comparators])
    if isinstance(node, ast.IfExp):
        return any(_is_device_expr(c, traced, jit_callables) for c in (node.body, node.orelse))
    return False


def _is_trace_guard(node: ast.AST) -> bool:
    """``not is_traced(x)`` — the conjunct that makes an eager-only check trace-dead."""
    return (
        isinstance(node, ast.UnaryOp)
        and isinstance(node.op, ast.Not)
        and isinstance(node.operand, ast.Call)
        and _final_name(node.operand.func) == "is_traced"
    )


def _branches_on_traced(node: ast.AST, traced: Set[str], jit_callables: Set[str]) -> bool:
    """Does this if/while test make a data-dependent decision on a traced value?

    Trace-safe constructs are excluded: ``is``/``in`` comparisons (identity and dict-key
    membership are host decisions), comparisons against string literals (config dispatch),
    shape/dtype attribute reads, host predicates (``len``/``isinstance``/…), explicit
    ``jax.device_get`` reads (the sanctioned, counted sync), and conjunctions guarded by
    ``not is_traced(...)`` — the repo's idiom for eager-only checks, which are dead under
    trace by construction (``is_traced`` returns True for tracers, so the guard
    short-circuits before the data-dependent operand ever evaluates).
    """
    if isinstance(node, ast.BoolOp):
        if isinstance(node.op, ast.And) and any(_is_trace_guard(v) for v in node.values):
            return False
        return any(_branches_on_traced(v, traced, jit_callables) for v in node.values)
    if isinstance(node, ast.UnaryOp):
        return _branches_on_traced(node.operand, traced, jit_callables)
    if isinstance(node, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for op in node.ops):
            return False
        operands = [node.left, *node.comparators]
        if any(isinstance(c, ast.Constant) and isinstance(c.value, str) for c in operands):
            return False
        return any(_branches_on_traced(c, traced, jit_callables) for c in operands)
    if isinstance(node, ast.Call):
        fn = _final_name(node.func)
        if fn in _STATIC_CALLS or fn in _HOST_FINAL or fn == "device_get":
            return False
        if _is_device_expr(node, traced, jit_callables):  # covers x.sum(), jnp.any(x), ...
            return True
        return any(
            _branches_on_traced(a, traced, jit_callables)
            for a in [*node.args, *(kw.value for kw in node.keywords)]
        )
    if isinstance(node, (ast.Name, ast.Attribute, ast.Subscript, ast.BinOp, ast.IfExp)):
        return _is_device_expr(node, traced, jit_callables)
    return False


def _finding(rule: str, path: str, node: ast.AST, lines: Sequence[str], message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    snippet = lines[line - 1].strip() if 0 < line <= len(lines) else ""
    return Finding(rule=rule, path=path, line=line, col=getattr(node, "col_offset", 0),
                   message=message, snippet=snippet)


# ================================================================================= rules
def _rule_tpu001(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    out: List[Finding] = []
    for info in model.functions:
        traced, jit_callables = model.traced_names(info)
        where = "inside jit-traced code (fails or constant-folds at trace time)" if info.jit \
            else "in eager per-call code (blocking device→host round-trip)"
        sfx = _via_suffix(info.via)
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            # guarded eager-only region: the `is_traced` guard IS the sanctioned,
            # deliberate host read — flagging it would punish the recommended idiom
            if model.is_trace_dead(info, node):
                continue
            # x.item()
            if isinstance(node.func, ast.Attribute) and node.func.attr == "item" and not node.args:
                base = node.func.value
                dotted = _dotted(base)
                host_rooted = dotted is not None and dotted[0] in ("np", "numpy")
                if not host_rooted:
                    out.append(_finding(
                        "TPU001", path, node, lines,
                        f".item() on an array value {where}; read once via jax.device_get(...)"
                        f" and keep per-step code device-only{sfx}",
                    ))
                continue
            # float(x) / int(x) / bool(x) / complex(x)
            if isinstance(node.func, ast.Name) and node.func.id in ("float", "int", "bool", "complex") \
                    and len(node.args) == 1 and not node.keywords:
                arg = node.args[0]
                if _is_device_expr(arg, traced, jit_callables):
                    out.append(_finding(
                        "TPU001", path, node, lines,
                        f"{node.func.id}() coerces a device array value to a host scalar {where};"
                        f" use jax.device_get(...) for a deliberate, counted sync{sfx}",
                    ))
    return out


def _rule_tpu002(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    out: List[Finding] = []
    for info in model.functions:
        if not info.jit:
            continue
        traced, jit_callables = model.traced_names(info)
        if not traced:
            continue
        for node in _scoped_walk(info.node):
            if isinstance(node, (ast.If, ast.While)) and not model.is_trace_dead(info, node) \
                    and _branches_on_traced(node.test, traced, jit_callables):
                kw = "while" if isinstance(node, ast.While) else "if"
                out.append(_finding(
                    "TPU002", path, node, lines,
                    f"data-dependent Python `{kw}` on a traced value inside jit-traced"
                    f" {info.name!r}; use jnp.where/lax.cond (or declare the driving argument"
                    f" in static_argnames){_via_suffix(info.via)}",
                ))
    return out


def _guarded_try_spans(info: _FuncInfo) -> List[Tuple[int, int]]:
    """Line spans of ``try`` bodies whose handlers catch ``Exception`` (or everything).

    A host-numpy call wrapped this way is the deliberate concretize-or-bail idiom: on a
    tracer the conversion raises, the handler takes the traced path, and the eager path
    gets the host value — trace-safe by construction.
    """
    spans: List[Tuple[int, int]] = []
    for node in _scoped_walk(info.node):
        if not isinstance(node, ast.Try):
            continue
        broad = any(
            h.type is None or _final_name(h.type) == "Exception" for h in node.handlers
        )
        if broad and node.body:
            spans.append((
                node.body[0].lineno,
                getattr(node.body[-1], "end_lineno", None) or node.body[-1].lineno,
            ))
    return spans


def _rule_tpu003(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    out: List[Finding] = []
    for info in model.functions:
        if not info.jit:
            continue
        traced, jit_callables = model.traced_names(info)
        if not traced:
            continue
        try_spans = _guarded_try_spans(info)
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None or dotted[0] not in ("np", "numpy") or len(dotted) < 2:
                continue
            if model.is_trace_dead(info, node) or any(
                lo <= node.lineno <= hi for lo, hi in try_spans
            ):
                continue
            arg_nodes = [*node.args, *(kw.value for kw in node.keywords)]
            if any(_is_device_expr(a, traced, jit_callables) for a in arg_nodes):
                out.append(_finding(
                    "TPU003", path, node, lines,
                    f"host numpy op {'.'.join(dotted)}(...) applied to a traced value inside"
                    f" jit-traced {info.name!r}; use the jnp equivalent or hoist the op out of"
                    f" the traced region{_via_suffix(info.via)}",
                ))
    return out


def _rule_tpu004(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    out: List[Finding] = []

    def config_params(fnode: ast.AST) -> List[str]:
        """Parameters whose default/annotation says 'host config': str or bool."""
        args = fnode.args
        named: List[str] = []
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            v = _const_value(d)
            if isinstance(v, (str, bool)):
                named.append(a.arg)
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and isinstance(_const_value(d), (str, bool)):
                named.append(a.arg)
        for a in pos + args.kwonlyargs:
            if a.arg not in named and a.annotation is not None \
                    and _final_name(a.annotation) in ("str", "bool"):
                named.append(a.arg)
        return named

    def check(site: ast.AST, target: _FuncInfo, statics: Set[str], argnums: Set[int]) -> None:
        statics = statics | model._argnums_to_names(target.node, argnums)
        missing = [p for p in config_params(target.node) if p not in statics]
        if missing:
            out.append(_finding(
                "TPU004", path, site, lines,
                f"jax.jit of {target.name!r} leaves config parameter(s)"
                f" {', '.join(repr(m) for m in missing)} non-static — every distinct value"
                " retraces the kernel (recompile churn; the runtime twin is obs' TPU004"
                " recompile-churn warning). Declare them in static_argnames",
            ))

    # decorator form
    for info in model.functions:
        for dec in info.node.decorator_list:
            wrap = model._jit_wrap_of_decorator(dec)
            if wrap is not None:
                check(dec, info, wrap[0], wrap[1])
    # call form: jax.jit(fn_name, ...)
    for node in ast.walk(model.tree):
        if not (isinstance(node, ast.Call) and _final_name(node.func) in ("jit", "pjit")):
            continue
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue
        candidates = model.by_name.get(node.args[0].id, [])
        if len(candidates) != 1:  # ambiguous resolution — do not guess
            continue
        check(
            node, candidates[0],
            model._statics_from_keywords(node.keywords),
            model._static_nums_from_keywords(node.keywords),
        )
    return out


def _default_spec(node: ast.AST) -> Dict[str, Any]:
    """dtype/value facts about an ``add_state`` default expression (best-effort)."""
    spec: Dict[str, Any] = {"dtype": None, "value": _NOT_CONST, "is_list": False}
    v = _const_value(node)
    if v is not _NOT_CONST:
        spec["value"] = v
        spec["dtype"] = "int" if isinstance(v, int) and not isinstance(v, bool) else "float"
        return spec
    if isinstance(node, (ast.List, ast.Tuple)):
        spec["is_list"] = True
        return spec
    if not isinstance(node, ast.Call):
        return spec
    final = _final_name(node.func)
    dtype_node = None
    for kw in node.keywords:
        if kw.arg == "dtype":
            dtype_node = kw.value
    if final in ("zeros", "ones") :
        spec["value"] = 0.0 if final == "zeros" else 1.0
        if dtype_node is None and len(node.args) > 1:
            dtype_node = node.args[1]
    elif final == "full":
        if len(node.args) > 1:
            spec["value"] = _const_value(node.args[1])
        if dtype_node is None and len(node.args) > 2:
            dtype_node = node.args[2]
    elif final in ("array", "asarray"):
        if node.args:
            spec["value"] = _const_value(node.args[0])
            if dtype_node is None:
                inner = spec["value"]
                if isinstance(inner, int) and not isinstance(inner, bool):
                    spec["dtype"] = "int"  # weak-typed: lands as int32 on device
        if dtype_node is None and len(node.args) > 1:
            dtype_node = node.args[1]
    if dtype_node is not None:
        dname = _final_name(dtype_node) or (
            dtype_node.value if isinstance(dtype_node, ast.Constant) else None
        )
        if isinstance(dname, str):
            spec["dtype"] = dname
    return spec


def _rule_tpu005(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    out: List[Finding] = []
    sum_states_by_class: Dict[str, Set[str]] = {}
    fx_by_class_state: Dict[Tuple[str, str], Set[Any]] = {}
    calls: List[Tuple[ast.Call, str, Any, Dict[str, Any], Optional[str]]] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func) != ["self", "add_state"]:
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant):
            continue
        state_name = node.args[0].value
        fx_node = node.args[2] if len(node.args) > 2 else None
        for kw in node.keywords:
            if kw.arg == "dist_reduce_fx":
                fx_node = kw.value
        if fx_node is None or not isinstance(fx_node, ast.Constant):
            continue
        fx = fx_node.value
        if len(node.args) < 2:
            continue
        spec = _default_spec(node.args[1])
        owner = _owning_class(model, node)
        if owner is not None:
            fx_by_class_state.setdefault((owner, state_name), set()).add(
                ("list", fx) if spec["is_list"] else ("tensor", fx)
            )
        calls.append((node, state_name, fx, spec, owner))
    for node, state_name, fx, spec, owner in calls:
        if spec["is_list"]:
            continue
        # a state registered under several reduce-fx/shape variants (config-dependent
        # __init__ branches) has no single contract to check against — skip it
        if owner is not None and len(fx_by_class_state.get((owner, state_name), set())) > 1:
            continue
        dtype, value = spec["dtype"], spec["value"]
        if fx == "sum":
            if owner is not None:
                sum_states_by_class.setdefault(owner, set()).add(state_name)
            if isinstance(dtype, str) and "int" in dtype and "64" not in dtype and "uint64" not in dtype:
                width = dtype if dtype != "int" else "int32 (weak-typed int default)"
                out.append(_finding(
                    "TPU005", path, node, lines,
                    f"state {state_name!r} is a {width} accumulator under dist_reduce_fx='sum' —"
                    " overflows silently at ~2.1e9 accumulated count; use a float or int64 default",
                ))
            if isinstance(value, (int, float)) and value != 0:
                out.append(_finding(
                    "TPU005", path, node, lines,
                    f"state {state_name!r} has non-zero default {value!r} under"
                    " dist_reduce_fx='sum' — replica sum adds the default once per device;"
                    " sum-reduced states need zero defaults",
                ))
        elif fx in ("min", "max") and isinstance(value, (int, float)) and value == 0:
            bound = "floor" if fx == "max" else "ceiling"
            out.append(_finding(
                "TPU005", path, node, lines,
                f"state {state_name!r} has zero default under dist_reduce_fx={fx!r} — zero acts"
                f" as a hidden {bound} for {'negative' if fx == 'max' else 'positive'} values;"
                " initialise with -inf/+inf (or the identity of the reduction)",
            ))
    # sum-reduced states assigned non-additively inside _update
    for info in model.functions:
        if info.name != "_update" or info.cls not in sum_states_by_class:
            continue
        state_param = _state_param_name(info.node)
        if state_param is None:
            continue
        # names that (transitively) carry a read of the previous state: direct uses of the
        # state param plus locals assigned from expressions that reference one
        state_reading: Set[str] = {state_param}
        assigns: List[Tuple[List[ast.AST], ast.AST]] = []
        for node in _scoped_walk(info.node):
            if isinstance(node, ast.Assign):
                assigns.append((list(node.targets), node.value))
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                assigns.append(([node.target], node.value))
            elif isinstance(node, ast.AugAssign):
                assigns.append(([node.target], node.value))
        for _ in range(4):
            changed = False
            for targets, value in assigns:
                if any(isinstance(s, ast.Name) and s.id in state_reading for s in ast.walk(value)):
                    for name in model._target_names(targets):
                        if name not in state_reading:
                            state_reading.add(name)
                            changed = True
            if not changed:
                break
        for node in _scoped_walk(info.node):
            if not (isinstance(node, ast.Return) and isinstance(node.value, ast.Dict)):
                continue
            for key, val in zip(node.value.keys, node.value.values):
                if not (isinstance(key, ast.Constant) and key.value in sum_states_by_class[info.cls]):
                    continue
                reads_state = any(
                    isinstance(sub, ast.Name) and sub.id in state_reading for sub in ast.walk(val)
                )
                if not reads_state:
                    out.append(_finding(
                        "TPU005", path, val, lines,
                        f"sum-reduced state {key.value!r} is returned without reading the"
                        f" previous state ({state_param!r}) — assignment replaces instead of"
                        " accumulating, which breaks multi-batch and cross-replica sums",
                    ))
    return out


def _owning_class(model: _ModuleModel, node: ast.AST) -> Optional[str]:
    for cname, cnode in model.class_nodes.items():
        for sub in ast.walk(cnode):
            if sub is node:
                return cname
    return None


def _state_param_name(fnode: ast.AST) -> Optional[str]:
    params = [a.arg for a in fnode.args.posonlyargs + fnode.args.args if a.arg not in ("self", "cls")]
    return params[0] if params else None


def _is_const_arg(node: ast.AST) -> bool:
    if _const_value(node) is not _NOT_CONST:
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_const_arg(el) for el in node.elts)
    dotted = _dotted(node)
    if dotted is not None and dotted[0] in ("jnp", "np", "numpy", "jax"):
        return True  # dtype references like jnp.float32
    return False


def _rule_tpu006(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    out: List[Finding] = []
    for info in model.functions:
        if info.jit:
            continue  # inside jit, constants are baked into the compiled program — free
        hot = info.hot or info.name in _HOT_EXACT or info.name.startswith(_HOT_PREFIXES)
        if not hot:
            continue
        sfx = _via_suffix(info.hot_via)
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None or dotted[0] != "jnp" or dotted[-1] not in _CONST_BUILDERS:
                continue
            arg_nodes = [*node.args, *(kw.value for kw in node.keywords)]
            if arg_nodes and all(_is_const_arg(a) for a in arg_nodes):
                out.append(_finding(
                    "TPU006", path, node, lines,
                    f"fresh device constant {'.'.join(dotted)}(...) built inside per-step hot"
                    f" path {info.name!r} — one host→device upload per call; hoist it to a"
                    f" module/instance-level constant built once{sfx}",
                ))
    return out


def _donating_argnums(node: ast.AST) -> Optional[Set[int]]:
    """Literal ``donate_argnums`` positions of a jit-producing expression, or None.

    Unwraps ``jax.jit(f, donate_argnums=...)``, the AOT chain ``jax.jit(f, donate_argnums=
    ...).lower(...).compile()``, and ``functools.partial(jax.jit, donate_argnums=...)``.
    Returns an empty set when donation is declared but the positions are not literal —
    the callable is known-donating, but no specific argument can be tracked.
    """
    while (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in ("lower", "compile")
    ):
        node = node.func.value
    if not isinstance(node, ast.Call):
        return None
    fn = _final_name(node.func)
    if fn == "partial" and node.args and _final_name(node.args[0]) in ("jit", "pjit"):
        pass
    elif fn not in ("jit", "pjit"):
        return None
    nums: Set[int] = set()
    found = False
    for kw in node.keywords:
        if kw.arg != "donate_argnums":
            continue
        found = True
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            nums.add(v.value)
        elif isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            for el in v.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    nums.add(el.value)
        else:  # declared via a variable/expression: donating, positions unknown
            return set()
    return nums if found else None


def _rule_tpu007(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    out: List[Finding] = []
    for info in model.functions:
        # (1) locally-bound donating callables: f = jax.jit(step, donate_argnums=(0,))[...]
        donators: Dict[str, Set[int]] = {}
        rebinds: Dict[str, List[int]] = {}
        for node in _scoped_walk(info.node):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            else:
                continue
            for name in model._target_names(targets):
                rebinds.setdefault(name, []).append(node.lineno)
            nums = _donating_argnums(value)
            if nums is not None:
                for name in model._target_names(targets):
                    donators[name] = nums
        if not donators:
            continue
        # (2) donation sites: names handed to a donating callable at a donated position
        donated_at: Dict[str, int] = {}
        for node in _scoped_walk(info.node):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                continue
            for idx in donators.get(node.func.id, ()):
                if idx < len(node.args) and isinstance(node.args[idx], ast.Name):
                    name = node.args[idx].id
                    donated_at[name] = max(node.lineno, donated_at.get(name, 0))
        if not donated_at:
            continue
        # (3) reads after the donation site with no intervening rebind: the buffer is gone
        for node in _scoped_walk(info.node):
            if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
                continue
            dline = donated_at.get(node.id)
            if dline is None or node.lineno <= dline:
                continue
            if any(dline <= rl <= node.lineno for rl in rebinds.get(node.id, ())):
                continue
            out.append(_finding(
                "TPU007", path, node, lines,
                f"{node.id!r} was donated to a compiled dispatch on line {dline} and is read"
                " afterwards — donated buffers are deleted (reads raise or return garbage);"
                " rebind the name to the dispatch output or drop donate_argnums for it",
            ))
    return out


def _rule_tpu008(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Bare ``assert`` whose test depends on a traced value, inside a jit context.

    Such an assert cannot validate anything at runtime: if the test stays abstract it
    either fails at trace time (TracerBoolConversionError — a crash, not a check) or, when
    the expression constant-folds, is baked away entirely; and under ``python -O`` asserts
    vanish altogether. Shape/dtype asserts (static metadata) are trace-time checks and
    stay clean.
    """
    out: List[Finding] = []
    for info in model.functions:
        if not info.jit:
            continue
        traced, jit_callables = model.traced_names(info)
        if not traced:
            continue
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Assert) or model.is_trace_dead(info, node):
                continue
            if _branches_on_traced(node.test, traced, jit_callables):
                out.append(_finding(
                    "TPU008", path, node, lines,
                    f"bare `assert` on a traced value inside jit-traced {info.name!r} — the"
                    " test is compiled away (or crashes the trace), so it validates nothing"
                    " at runtime; hoist the check to the eager host path or fold it into the"
                    f" graph (jnp.where / a counted guard state){_via_suffix(info.via)}",
                ))
    return out


#: obs module-level hooks that are host side effects (counters/state mutation per call)
_OBS_HOOK_NAMES = {"bump", "count_dispatch", "device_sync", "record_trace", "metric_span"}
#: telemetry registry methods whose call sites are per-call side effects
_TELEMETRY_METHODS = {"counter", "timer", "histogram", "event", "span", "inc", "observe", "record"}


def _rule_tpu009(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Telemetry/``obs`` registry calls inside jit-traced code.

    A counter bump or span inside a traced function executes while jax TRACES the Python
    body — once per compilation, never per step. The instrument silently reads as "this
    hot path fired N times" when it really means "this kernel compiled N times"; worse, a
    span's wall time measures tracing, not execution. Deliberate trace-time recording
    (the engine's ``record_trace`` hook, ``sync_state``'s trace-time event) belongs in
    functions that are NOT themselves jit roots — this rule flags instruments reachable
    from a jit context, where per-step counting silently stops counting.
    """
    out: List[Finding] = []
    for info in model.functions:
        if not info.jit:
            continue
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call) or model.is_trace_dead(info, node):
                continue
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            hit = None
            if dotted[0] in ("obs", "telemetry"):
                if dotted[0] == "obs" and len(dotted) == 2 and dotted[1] in _OBS_HOOK_NAMES:
                    hit = ".".join(dotted)
                elif "telemetry" in dotted[:2] and dotted[-1] in _TELEMETRY_METHODS:
                    hit = ".".join(dotted)
            if hit is None:
                continue
            out.append(_finding(
                "TPU009", path, node, lines,
                f"telemetry call {hit}(...) inside jit-traced {info.name!r} executes at"
                " TRACE time only (once per compilation, not per step) — the count/span"
                " silently stops recording on cached executions; hoist the instrument to"
                " the eager caller or fold the quantity into the program as a state"
                f" output{_via_suffix(info.via)}",
            ))
    return out


def _metric_ctor_names(model: _ModuleModel) -> Set[str]:
    """Names this module imported from a metrics package (``from ...metrics import X``).

    The boundary TPU010 draws for "is this call a Metric constructor": a call to a name
    imported from a module whose path mentions ``metrics``, or to any name ending in
    ``Metric`` (``SumMetric``, a local ``MyMetric`` subclass). Locally defined classes
    whose names don't say so are invisible — under-reporting beats flagging every loop
    that calls ``.update()`` on arbitrary objects.
    """
    names: Set[str] = set()
    for node in ast.walk(model.tree):
        if isinstance(node, ast.ImportFrom) and node.module and "metrics" in node.module:
            for alias in node.names:
                names.add(alias.asname or alias.name)
    return names


def _rule_tpu010(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Host-side per-key loop driving a dict/list of Metric instances.

    The shape that serves N tenants as N instances::

        per_user = {uid: SumMetric() for uid in users}
        for uid, m in per_user.items():
            m.update(values[uid])             # one dispatch PER KEY per step

    Every iteration is a separate kernel launch plus jit argument processing — the
    host-overhead regime the engine's fused tiers exist to kill, multiplied by the key
    count. ``torchmetrics_tpu.keyed.KeyedMetric(template, num_keys=N)`` holds all N
    streams in one ``[N, ...]`` state table and folds a mixed-key batch in ONE launch.

    Boundary: only fires when the iterated container was built *in the same function* as
    a dict/list/set (literal or comprehension) of Metric-constructor calls — a loop over
    ``self.metrics`` or an argument stays clean (the analyzer cannot see what it holds;
    library containers like ``MetricCollection`` iterate members legitimately).
    """
    ctor_names = _metric_ctor_names(model)

    def is_metric_ctor(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = _final_name(node.func)
        return bool(name) and (name.endswith("Metric") or name in ctor_names)

    out: List[Finding] = []
    for info in model.functions:
        per_key: Set[str] = set()
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            elems: List[ast.AST] = []
            if isinstance(value, ast.DictComp):
                elems = [value.value]
            elif isinstance(value, (ast.ListComp, ast.SetComp)):
                elems = [value.elt]
            elif isinstance(value, ast.Dict):
                elems = list(value.values)
            elif isinstance(value, (ast.List, ast.Set, ast.Tuple)):
                elems = list(value.elts)
            if elems and all(is_metric_ctor(e) for e in elems):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        per_key.add(t.id)
        if not per_key:
            continue
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.For):
                continue
            container = None
            it = node.iter
            if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute) and (
                it.func.attr in ("values", "items") and isinstance(it.func.value, ast.Name)
            ):
                container = it.func.value.id
            elif isinstance(it, ast.Name):
                container = it.id
            loop_targets = {
                t.id for t in ast.walk(node.target) if isinstance(t, ast.Name)
            } if container in per_key else set()
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute)):
                    continue
                if sub.func.attr not in ("update", "forward"):
                    continue
                base = sub.func.value
                hit = (
                    (isinstance(base, ast.Name) and base.id in loop_targets)
                    or (
                        isinstance(base, ast.Subscript)
                        and isinstance(base.value, ast.Name)
                        and base.value.id in per_key
                    )
                )
                if hit:
                    which = base.id if isinstance(base, ast.Name) else base.value.id  # type: ignore[union-attr]
                    out.append(_finding(
                        "TPU010", path, sub, lines,
                        f"per-key Metric loop: `.{sub.func.attr}()` on instances of"
                        f" {which!r} dispatches one kernel per key per step — route the"
                        " mixed-key batch through keyed.KeyedMetric(template, num_keys=N)"
                        " (one fused launch updates every key; docs/keyed.md)",
                    ))
                    break
    return out


#: full-state gather entry points TPU011 watches for (the replicated sync primitives)
_FULL_GATHER_NAMES = frozenset(
    {"gather_all_arrays", "gather_all_tensors", "process_allgather", "all_gather"}
)


def _sharded_names_in(info: _FuncInfo) -> Set[str]:
    """Names ``.shard(...)``-placed in this function (shared by TPU011 and TPU013)."""
    sharded: Set[str] = set()
    for node in _scoped_walk(info.node):
        call = None
        targets: List[str] = []
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
        if call is None or not isinstance(call.func, ast.Attribute) or call.func.attr != "shard":
            continue
        base = call.func.value
        if isinstance(base, ast.Name):
            sharded.add(base.id)
        sharded.update(targets)  # m = SumMetric().shard(mesh) / m2 = m.shard(mesh)
    return sharded


def _rule_tpu011(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Replicated full-state gather on a metric that declared a sharded spec.

    The regression the sharded engine exists to remove::

        km = KeyedMetric(SumMetric(), num_keys=N).shard(mesh)   # tenant axis partitioned
        ...
        pieces = gather_all_arrays(km.metric_state["sum_value"])  # W full copies back!

    A sharded state syncs by reduce-scatter + slab assembly (received ``≈ 2×state``,
    ``parallel/sync.py``); routing it through ``gather_all_arrays`` /
    ``multihost_utils.process_allgather`` / a raw ``lax.all_gather`` re-replicates every
    shard on every rank — ``world × state`` bytes plus ``world`` resident copies, exactly
    the layout ``shard()`` was called to avoid. Let ``compute()``/``process_sync`` drive
    the sync (they pick the sharded path from the declared specs) instead of gathering by
    hand.

    Boundary: only fires when ``.shard(...)`` was called on the object *in the same
    function* (directly or via ``m = X.shard(mesh)`` — ``shard`` returns its metric), and
    a watched gather call takes anything derived from that name. Cross-function sharding
    is invisible by design — under-reporting beats flagging every gather in the sync
    layer itself.
    """
    out: List[Finding] = []
    for info in model.functions:
        sharded = _sharded_names_in(info)
        if not sharded:
            continue
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            fname = _final_name(node.func)
            if fname not in _FULL_GATHER_NAMES:
                continue
            hit = None
            for arg in [*node.args, *(kw.value for kw in node.keywords)]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in sharded:
                        hit = sub.id
                        break
                if hit:
                    break
            if hit is None:
                continue
            out.append(_finding(
                "TPU011", path, node,
                lines,
                f"full-state `{fname}(...)` on {hit!r}, which declared a sharded spec"
                " via .shard(...): the gather re-replicates every shard on every rank"
                " (world x state bytes + world resident copies) — let compute()/"
                "process_sync drive the reduce-scatter sharded sync instead"
                " (docs/distributed.md 'Sharded state')",
            ))
    return out


# ------------------------------------------------------------------------ TPU012 helpers
#: calls that END a donated-read window — the engine's commit/recover seams. Defs carrying
#: the `# jaxlint: donation-commit` marker (ops/dispatch.py) extend this set in project
#: mode; the built-ins keep single-file analysis of metric.py honest without it.
_COMMIT_BARRIERS = frozenset({"commit_step", "recover_failed_step", "commit_donated", "abort_donated"})
_COMMIT_MARKER = "jaxlint: donation-commit"
#: def-line marker declaring that CALLING this function donates the given positional args
_DONATES_RE = re.compile(r"#\s*jaxlint:\s*donates\((\d+(?:\s*,\s*\d+)*)\)")


def _assign_of(node: ast.AST) -> Tuple[List[ast.AST], Optional[ast.AST]]:
    if isinstance(node, ast.Assign):
        return list(node.targets), node.value
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return [node.target], node.value
    if isinstance(node, ast.AugAssign):
        return [node.target], node.value
    return [], None


def _aot_compile_donations(call: ast.Call) -> Optional[Set[int]]:
    """Literal donated positions of an ``aot_compile(fn, ex, donate_argnums=...)`` call.

    ``aot_compile`` (ops/dispatch.py) returns a compiled executable that donates exactly
    the positions its ``donate_argnums`` keyword names — the AOT twin of the jit chain
    :func:`_donating_argnums` unwraps. Non-literal positions mark the result as donating
    with nothing trackable (empty set); no keyword means no donation (None).
    """
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            nums = {el.value for el in v.elts if isinstance(el, ast.Constant) and isinstance(el.value, int)}
            return nums if len(nums) == len(v.elts) else set()
        return set()
    return None


def _rule_tpu012(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Donation-lifetime race: donated buffer (or a sibling alias) read before re-commit.

    The static race detector behind the engine's runtime ``StateStore`` generation guard:
    between handing state buffers to a donating executable and the commit/recover seam
    (``commit_step`` / ``commit_donated`` / ``recover_failed_step`` / ``abort_donated``,
    plus any def carrying the ``# jaxlint: donation-commit`` marker), every donated buffer
    is DELETED — a read in that window raises jax's deleted-array error, or silently reads
    reclaimed memory on backends that ignore donation.

    What this adds over the literal-only TPU007:

    - **sibling aliases**: ``alias = state`` taken before the donation dies with the
      donated name; reads through the alias are the under-reported half of TPU007.
    - **cross-boundary donators**: callables annotated ``# jaxlint: donates(i, ...)`` on
      their def line (the engine's ``dispatch_step``), ``aot_compile(...,
      donate_argnums=...)`` results, and — in project mode — parameters that *receive* a
      donating callable at a call site one or two hops away (``info.donating_params``).
    - **commit awareness**: reads after the seam are clean (the engine rebinds state
      through the store there), so the rule models the true hazard window instead of
      flagging the whole rest of the function.
    """
    out: List[Finding] = []
    annotated: Dict[str, Set[int]] = dict(getattr(model, "project_donators", None) or {})
    barriers: Set[str] = set(_COMMIT_BARRIERS) | set(getattr(model, "project_barriers", None) or ())
    for info in model.functions:
        dl = info.node.lineno
        src = lines[dl - 1] if 0 < dl <= len(lines) else ""
        m = _DONATES_RE.search(src)
        if m:
            annotated[info.name] = {int(x) for x in m.group(1).split(",")}
        if _COMMIT_MARKER in src:
            barriers.add(info.name)
    # module-scope donating callables (step = jax.jit(k, donate_argnums=...)) are visible
    # to every function in the file through the closure
    module_donators: Dict[str, Set[int]] = {}
    for node in _scoped_walk(model.tree):
        targets, value = _assign_of(node)
        if value is None:
            continue
        nums = _donating_argnums(value)
        if nums is None and isinstance(value, ast.Call) and _final_name(value.func) == "aot_compile":
            nums = _aot_compile_donations(value)
        if nums:
            for name in model._target_names(targets):
                module_donators[name] = set(nums)
    for info in model.functions:
        # (1) donating callables visible in this function body (or received as params,
        # or bound at module scope — closure visibility)
        donators: Dict[str, Tuple[Set[int], str, Optional[Tuple[str, ...]]]] = {
            name: (set(nums), "module", None) for name, nums in module_donators.items()
        }
        donators.update(
            (pname, (set(nums), "param", info.via))
            for pname, nums in info.donating_params.items()
        )
        rebinds: Dict[str, List[int]] = {}
        alias_edges: List[Tuple[str, str, int]] = []
        for node in _scoped_walk(info.node):
            targets, value = _assign_of(node)
            if value is None:
                continue
            for name in model._target_names(targets):
                rebinds.setdefault(name, []).append(node.lineno)
            if isinstance(value, ast.Name):
                for name in model._target_names(targets):
                    alias_edges.append((name, value.id, node.lineno))
            nums = _donating_argnums(value)
            kind = "local"
            if nums is None and isinstance(value, ast.Call) and _final_name(value.func) == "aot_compile":
                nums = _aot_compile_donations(value)
                kind = "aot"
            if nums is not None:
                for name in model._target_names(targets):
                    donators[name] = (nums, kind, None)
        # (2) donation sites and commit barriers (multi-line calls donate at end_lineno)
        donated: Dict[str, Tuple[int, str, Optional[Tuple[str, ...]]]] = {}
        barrier_lines: List[int] = []
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            fname = _final_name(node.func)
            if fname in barriers:
                barrier_lines.append(getattr(node, "end_lineno", None) or node.lineno)
                continue
            spec = None
            if isinstance(node.func, ast.Name) and node.func.id in donators:
                spec = donators[node.func.id]
            elif fname in annotated:
                spec = (annotated[fname], "annotated", None)
            if spec is None:
                continue
            nums, kind, via = spec
            dline = getattr(node, "end_lineno", None) or node.lineno
            for idx in nums:
                if idx < len(node.args) and isinstance(node.args[idx], ast.Name):
                    nm = node.args[idx].id
                    prev = donated.get(nm)
                    if prev is None or dline > prev[0]:
                        donated[nm] = (dline, kind, via)
        if not donated:
            continue
        # (3) close each donated name over aliases established BEFORE its donation
        watch: Dict[str, Tuple[str, int, str, Optional[Tuple[str, ...]]]] = {}
        for dname, (dline, kind, via) in donated.items():
            group = {dname}
            changed = True
            while changed:
                changed = False
                for a, b, ln in alias_edges:
                    if ln > dline:
                        continue
                    if (a in group) != (b in group):
                        group |= {a, b}
                        changed = True
            for nm in group:
                if nm == dname and kind == "local":
                    continue  # the direct read of a locally-jit-donated name is TPU007's
                prev = watch.get(nm)
                if prev is None or dline > prev[1]:
                    watch[nm] = (dname, dline, kind, via)
        if not watch:
            continue
        # (4) reads inside the open window: after donation, before rebind/commit seam
        for node in _scoped_walk(info.node):
            if not (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)):
                continue
            spec2 = watch.get(node.id)
            if spec2 is None:
                continue
            dname, dline, kind, via = spec2
            if node.lineno <= dline:
                continue
            if any(dline < rl <= node.lineno for rl in rebinds.get(node.id, ())):
                continue
            if any(dline < bl < node.lineno for bl in barrier_lines):
                continue
            alias_part = "" if node.id == dname else f" (a pre-donation alias of {dname!r})"
            out.append(_finding(
                "TPU012", path, node, lines,
                f"{node.id!r}{alias_part} reads a buffer donated to a compiled dispatch on"
                f" line {dline}, before the commit/recover seam — donated buffers are"
                " deleted by XLA, so the read raises (or returns garbage on backends that"
                " ignore donation); commit the dispatch outputs first (commit_step /"
                f" commit_donated) or rebind the name{_via_suffix(via)}",
            ))
    return out


#: float folds whose result depends on element order (non-associative in float)
_ORDER_FOLDS = frozenset({"mean", "sum"})
#: concatenation builders whose cross-shard output order follows placement
_CAT_BUILDERS = frozenset({"concatenate", "dim_zero_cat", "hstack", "vstack", "stack", "append"})


def _rule_tpu013(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Sharding-consistency hazards on ``.shard()``-placed metric state.

    Two shapes, both scoped to functions that called ``.shard(...)`` themselves (the
    TPU011 boundary — cross-function sharding is invisible by design):

    - **hand mutation without a sharding constraint**: assigning into the placed state
      (``m.metric_state[...] = v``, ``m._state.tensors[...] = v``, or through a one-hop
      alias of either) with a value not wrapped in ``with_sharding_constraint``. The
      engine closes every update kernel under the declared constraints
      (``_effective_update``); a bare host-side write silently re-replicates the leaf,
      dropping the mesh layout every compiled tier expects.
    - **shard-order-dependent float fold**: ``mean``/``sum`` over a concatenation
      (``jnp.concatenate`` / ``dim_zero_cat`` / stacks) of the sharded object's state —
      cross-shard cat order follows placement, and float reduction is not associative,
      so the result changes with mesh shape.
    """
    out: List[Finding] = []
    for info in model.functions:
        sharded = _sharded_names_in(info)
        if not sharded:
            continue
        # one-hop state aliases: st = m.metric_state / st = m._state.tensors
        state_aliases: Set[str] = set()
        for node in _scoped_walk(info.node):
            if isinstance(node, ast.Assign):
                d = _dotted(node.value)
                if d and d[0] in sharded and len(d) > 1 and d[-1] in ("metric_state", "tensors"):
                    state_aliases.update(t.id for t in node.targets if isinstance(t, ast.Name))
        # (a) hand mutation of placed state without with_sharding_constraint
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not isinstance(t, ast.Subscript):
                    continue
                d = _dotted(t.value)
                which = None
                if d and d[0] in sharded and len(d) > 1 and d[-1] in ("metric_state", "tensors"):
                    which = d[0]
                elif d and len(d) == 1 and d[0] in state_aliases:
                    which = d[0]
                if which is None:
                    continue
                constrained = any(
                    isinstance(s, ast.Call) and _final_name(s.func) == "with_sharding_constraint"
                    for s in ast.walk(node.value)
                )
                if not constrained:
                    out.append(_finding(
                        "TPU013", path, node, lines,
                        f"state of {which!r} (placed via .shard(...)) is hand-mutated without"
                        " with_sharding_constraint — an unconstrained write silently"
                        " re-replicates the leaf, dropping the mesh layout every compiled"
                        " tier was built for; route the write through the engine's update"
                        " kernels, or wrap the value in jax.lax.with_sharding_constraint"
                        " with the declared spec (docs/distributed.md 'Sharded state')",
                    ))
        # (b) float fold over a cross-shard concatenation
        for node in _scoped_walk(info.node):
            if not (isinstance(node, ast.Call) and _final_name(node.func) in _ORDER_FOLDS):
                continue
            hit = None
            for arg in node.args:
                for cat in (s for s in ast.walk(arg)
                            if isinstance(s, ast.Call) and _final_name(s.func) in _CAT_BUILDERS):
                    for s in ast.walk(cat):
                        if isinstance(s, ast.Name) and (s.id in sharded or s.id in state_aliases):
                            hit = s.id
                            break
                    if hit:
                        break
                if hit:
                    break
            if hit is None:
                continue
            out.append(_finding(
                "TPU013", path, node, lines,
                f"float `{_final_name(node.func)}` fold over concatenated shards of"
                f" {hit!r} — cross-shard cat order follows placement and float reduction"
                " is not associative, so the result drifts with mesh shape; fix the"
                " order (sort by shard index) or reduce shard-locally before"
                " concatenating (the engine's reduce-scatter sync does exactly this)",
            ))
    return out


#: metric classes with a registered streaming-sketch twin. MIRRORS
#: ``torchmetrics_tpu.sketch.state.SKETCH_EQUIVALENTS`` — the analyzer is stdlib-only and
#: must never import the package (that pulls in jax), so the set is restated here; a sync
#: test (``tests/unittests/lint/test_tpu014.py``) fails when the two drift apart.
_SKETCH_EQUIVALENT_METRICS = frozenset({
    "BinaryPrecisionRecallCurve",
    "MulticlassPrecisionRecallCurve",
    "MultilabelPrecisionRecallCurve",
    "RetrievalMetric",
})


def _rule_tpu014(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Unbounded ``add_state(default=[], dist_reduce_fx="cat"/None)`` on a metric that has
    a registered sketch equivalent but offers no sketch wiring.

    The cat state is the slow tail the sketch subsystem exists to kill: state, snapshots,
    journals, and sync bytes all grow linearly with samples seen, and compute sorts the
    whole stream. A class in the sketch-equivalents registry (or subclassing one) that
    registers a cat/gather list state should at least OFFER the O(1) twin.

    Boundary — the rule stays silent when the class is sketch-wired: its ``__init__``
    exposes an ``approx`` parameter (or references ``self.approx``), or the module calls
    into ``torchmetrics_tpu.sketch`` (``register_sketch_state`` et al.). That keeps this
    repo's own wired curve/retrieval classes clean while flagging forks or new metrics
    that reintroduce the unbounded state without the escape hatch.
    """
    out: List[Finding] = []
    for cname, cnode in model.class_nodes.items():
        base_names = {b for n in cnode.bases if (b := _final_name(n))}
        if cname not in _SKETCH_EQUIVALENT_METRICS and not (
            base_names & _SKETCH_EQUIVALENT_METRICS
        ):
            continue
        wired = False
        for node in ast.walk(cnode):
            if isinstance(node, ast.arg) and node.arg == "approx":
                wired = True
                break
            if isinstance(node, ast.Attribute) and node.attr == "approx":
                wired = True
                break
            if isinstance(node, ast.Call):
                fname = _final_name(node.func)
                if fname in ("register_sketch_state", "hist_spec", "kll_spec", "countmin_spec"):
                    wired = True
                    break
        if wired:
            continue
        for node in ast.walk(cnode):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr != "add_state" or not isinstance(node.func.value, ast.Name):
                continue
            if node.func.value.id != "self" or len(node.args) < 2:
                continue
            default = node.args[1]
            if not (isinstance(default, ast.List) and not default.elts):
                continue
            fx: Any = None
            if len(node.args) >= 3:
                fx = _const_value(node.args[2])
            for kw in node.keywords:
                if kw.arg == "dist_reduce_fx":
                    fx = _const_value(kw.value)
            if fx not in ("cat", None):
                continue
            state_name = _const_value(node.args[0])
            out.append(_finding(
                "TPU014", path, node, lines,
                f"unbounded cat state {state_name!r} on {cname!r}, which has a registered"
                " streaming-sketch equivalent: state/snapshot/sync bytes grow with every"
                " sample and compute sorts the whole stream — offer approx='sketch'"
                " (fixed-size mergeable state, documented error bound; docs/sketches.md)",
            ))
    return out


# ------------------------------------------------------------------------ TPU015 helpers
#: host-blocking attribute calls the serving tier must never make on its drain path
_TPU015_BLOCKING_ATTRS = {"item", "tolist", "block_until_ready"}
_SERVE_PATH_MARK = re.compile(r"#\s*jaxlint:\s*serve-path\b")


def _is_serve_path_file(path: str) -> bool:
    """True for modules that ARE the serving tier (any ``serve`` directory segment)."""
    parts = path.replace("\\", "/").split("/")
    return "serve" in parts[:-1]


def _marked_serve_path(info: _FuncInfo, lines: Sequence[str]) -> bool:
    """``# jaxlint: serve-path`` on the def line, a decorator line, or the line above."""
    node = info.node
    first = min([node.lineno] + [d.lineno for d in getattr(node, "decorator_list", [])])
    for ln in range(max(1, first - 1), node.lineno + 1):
        if ln <= len(lines) and _SERVE_PATH_MARK.search(lines[ln - 1]):
            return True
    return False


def _rule_tpu015(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Host-blocking call reachable from an async serve/drain path.

    The serving tier's whole throughput story is that the drain thread only ever
    *dispatches* — ``update`` kernels, staging transfers — and never waits on the
    device: one ``.block_until_ready()`` (or an implicit sync via ``.item()`` /
    ``.tolist()`` / ``jax.device_get``) inside the drain serializes transfer with
    compute and the overlap evaporates; worse, under backpressure it stretches every
    enqueue's latency by a device roundtrip. Roots are functions in a ``serve/`` module
    or marked ``# jaxlint: serve-path``; the rule follows the intra-module call graph
    (plain and ``self.`` calls, plus nested helpers) from those roots — cross-module
    callees are out of scope (the engine applies batches through the metric's ordinary
    update path, whose own hazards have their own rules).
    """
    roots: List[_FuncInfo] = []
    file_is_serve = _is_serve_path_file(path)
    for info in model.functions:
        if file_is_serve or _marked_serve_path(info, lines):
            roots.append(info)
    if not roots:
        return []
    # fixpoint reachability over local calls + nested defs
    reachable: Set[int] = set()
    frontier = list(roots)
    while frontier:
        info = frontier.pop()
        if id(info) in reachable:
            continue
        reachable.add(id(info))
        frontier.extend(info.children)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            callees: List[_FuncInfo] = []
            if isinstance(node.func, ast.Name) and node.func.id in model.by_name:
                callees = model.by_name[node.func.id]
            elif (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr in model.by_name
            ):
                callees = [fi for fi in model.by_name[node.func.attr] if fi.cls is not None]
            frontier.extend(fi for fi in callees if id(fi) not in reachable)
    by_id = {id(fi): fi for fi in model.functions}
    out: List[Finding] = []
    seen_lines: Set[Tuple[int, int]] = set()
    for fid in reachable:
        info = by_id[fid]
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            blocked: Optional[str] = None
            if isinstance(node.func, ast.Attribute) and node.func.attr in _TPU015_BLOCKING_ATTRS:
                blocked = f".{node.func.attr}()"
            else:
                dotted = _dotted(node.func)
                if dotted and dotted[-1] == "device_get":
                    blocked = "jax.device_get"
            if blocked is None:
                continue
            key = (node.lineno, node.col_offset)
            if key in seen_lines:
                continue
            seen_lines.add(key)
            out.append(_finding(
                "TPU015", path, node, lines,
                f"host-blocking {blocked} in {info.qualname!r}, which is reachable from"
                " an async serve/drain path: the drain must only dispatch — a device"
                " sync here serializes transfer with compute and stalls every enqueue"
                " behind a roundtrip. Commit the future and read it after quiesce.",
            ))
    return out


# ------------------------------------------------------------------------ TPU016 helpers
#: span-factory call names whose result is a context manager that MUST be closed
_TPU016_SPAN_FACTORIES = {"span", "metric_span"}
#: serve-trace / live-series mutation hooks that are host side effects per call
#: (extends TPU009's registry-method set to the PR-12 trace/series API)
_TPU016_TRACE_HOOKS = {
    "mint", "enqueue_span", "shed_event", "coalesced_event", "dispatched_event",
    "apply_span", "committed_event", "failed_event", "abandoned_event",
    "fence_break_event", "note_thread", "push",
}


def _rule_tpu016(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Unclosed spans, and trace-ring/series mutation reachable from jit-traced code.

    Prong 1 (any function): a call to a span factory (``telemetry.span(...)`` /
    ``obs.metric_span(...)``) opens a timed scope whose ``__exit__`` records the event —
    begun outside a ``with`` item and never closed, the slice silently never lands in
    the trace (and its Timer never observes). Clean shapes: the call is a ``with``
    item; the result is assigned and later entered via ``with``; the result is
    assigned and ``.__exit__`` is called under ``try/finally``; or the call is
    returned (ownership passes to the caller, the factory idiom).

    Prong 2 (jit-traced functions only — TPU009's argument, new API): serve-trace
    stage emitters (``trace.enqueue_span`` etc.), ring pushes, and live-series
    ``.record(...)`` calls are host side effects; inside a traced body they run once
    per COMPILATION, so the span/series silently stops recording on cached executions.
    """
    out: List[Finding] = []
    for info in model.functions:
        # ---- prong 1: span lifecycle over every function ---------------------------
        with_exprs: Set[int] = set()
        entered_names: Set[str] = set()
        exited_names: Set[str] = set()
        returned: Set[int] = set()
        assigns: List[Tuple[str, ast.Call]] = []
        span_calls: List[ast.Call] = []

        def _is_span_call(node: ast.AST) -> bool:
            return (
                isinstance(node, ast.Call)
                and _final_name(node.func) in _TPU016_SPAN_FACTORIES
            )

        for node in _scoped_walk(info.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    if _is_span_call(item.context_expr):
                        with_exprs.add(id(item.context_expr))
                    elif isinstance(item.context_expr, ast.Name):
                        entered_names.add(item.context_expr.id)
            elif isinstance(node, ast.Return) and _is_span_call(node.value):
                returned.add(id(node.value))
            elif isinstance(node, ast.Assign) and _is_span_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        assigns.append((t.id, node.value))
            elif isinstance(node, ast.Try) and node.finalbody:
                for fin in node.finalbody:
                    for sub in ast.walk(fin):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "__exit__"
                            and isinstance(sub.func.value, ast.Name)
                        ):
                            exited_names.add(sub.func.value.id)
            if _is_span_call(node):
                span_calls.append(node)  # type: ignore[arg-type]

        closed_ids: Set[int] = set(with_exprs) | set(returned)
        for name, call in assigns:
            if name in entered_names or name in exited_names:
                closed_ids.add(id(call))
        for call in span_calls:
            if id(call) in closed_ids:
                continue
            out.append(_finding(
                "TPU016", path, call, lines,
                f"span opened by {_final_name(call.func)}(...) in {info.qualname!r} is"
                " never closed — not a `with` item, never entered, and no try/finally"
                " __exit__: the slice (and its timer observation) silently never"
                " records; wrap the scope in `with`, or close it in a finally block",
            ))

        # ---- prong 2: trace/series mutation inside jit-traced code -----------------
        if not info.jit:
            continue
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call) or model.is_trace_dead(info, node):
                continue
            hit: Optional[str] = None
            dotted = _dotted(node.func)
            if dotted is not None and dotted[-1] in _TPU016_TRACE_HOOKS and (
                "trace" in dotted[:-1] or "ring" in dotted[:-1] or dotted[0] == "ring"
            ):
                hit = ".".join(dotted)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "record"
                and isinstance(node.func.value, ast.Call)
                and _final_name(node.func.value.func) == "series"
            ):
                hit = "series(...).record"
            if hit is None:
                continue
            out.append(_finding(
                "TPU016", path, node, lines,
                f"serve-trace/series mutation {hit}(...) inside jit-traced"
                f" {info.name!r} executes at TRACE time only (once per compilation,"
                " not per step) — the span/series silently stops recording on cached"
                " executions; emit from the eager host caller"
                f"{_via_suffix(info.via)}",
            ))
    return out


# ------------------------------------------------------------------------ TPU017 helpers
#: wall-clock reads whose value gates behaviour non-reproducibly. time.perf_counter /
#: process_time are deliberately ABSENT: they are measurement clocks this codebase uses
#: for profiling, and their values never define metric semantics.
_TPU017_CLOCKS = {
    ("time", "time"),
    ("time", "time_ns"),
    ("time", "monotonic"),
    ("time", "monotonic_ns"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
    ("date", "today"),
}


def _rule_tpu017(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Wall-clock read inside jit-traced code or an eager per-step hot path.

    Two distinct failure modes behind one read:

    - **under jit** the call executes at TRACE time only — the "current time" is
      frozen into the compiled program, so any window boundary or decay horizon built
      on it silently stops moving after the first compilation (and forcing a retrace
      per step to "fix" it is the TPU004 churn hazard).
    - **on an eager per-step path** the value makes metric behaviour a function of the
      host's clock: window advances land on different batches across runs, a WAL
      replay (``snapshot + replay(journal)``) cannot reconstruct the same state, and
      the tier-equivalence/chaos bit-identity contracts quietly stop holding. The
      online window layer (``torchmetrics_tpu.online``) exists precisely to provide
      the deterministic alternative: update-count-driven advances.

    Hot-path detection matches TPU006's (name heuristics + the whole-program ``hot``
    mark); measurement-only clocks (``perf_counter``) are exempt.
    """
    out: List[Finding] = []
    for info in model.functions:
        in_jit = info.jit
        hot = (not in_jit) and (
            info.hot or info.name in _HOT_EXACT or info.name.startswith(_HOT_PREFIXES)
        )
        if not (in_jit or hot):
            continue
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None or len(dotted) < 2 or tuple(dotted[-2:]) not in _TPU017_CLOCKS:
                continue
            if in_jit and model.is_trace_dead(info, node):
                continue
            clock = ".".join(dotted[-2:])
            if in_jit:
                why = (
                    "executes at TRACE time only — the timestamp is frozen into the"
                    " compiled program, so time-gated behaviour silently stops moving"
                    f" after the first compilation{_via_suffix(info.via)}"
                )
            else:
                why = (
                    "makes per-step behaviour a function of the host clock —"
                    " irreproducible across runs and unreconstructable under WAL"
                    " replay; gate on an update/step count instead"
                    f" (torchmetrics_tpu.online advances that way){_via_suffix(info.hot_via)}"
                )
            out.append(_finding(
                "TPU017", path, node, lines,
                f"wall-clock read {clock}() in"
                f" {'jit-traced' if in_jit else 'per-step hot path'} {info.qualname!r} {why}",
            ))
    return out


# ------------------------------------------------------------------------ TPU018 helpers
#: lossy wire modes of SyncOptions(compression=...) (parallel/compress.py MODES minus "none")
_TPU018_LOSSY_MODES = {"bf16", "int8"}


def _tpu018_traceable_names(tree: ast.Module) -> Set[str]:
    """Names the module marks with the merge contract (``<name>.traceable = True``)."""
    marked: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Constant) and node.value.value is True):
            continue
        for t in node.targets:
            if isinstance(t, ast.Attribute) and t.attr == "traceable" and isinstance(t.value, ast.Name):
                marked.add(t.value.id)
    return marked


def _tpu018_sketch_imports(tree: ast.Module) -> Set[str]:
    """Local names imported from the sketch subsystem (merge-contract by provenance)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and "sketch" in (node.module or ""):
            names.update(a.asname or a.name for a in node.names)
        elif isinstance(node, ast.Import):
            names.update(
                (a.asname or a.name.split(".")[0])
                for a in node.names
                if "sketch" in a.name
            )
    return names


def _rule_tpu018(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Lossy sync compression configured beside a non-error-feedback-safe reduction.

    The compressed-collective codec keeps its exactness promises *structurally*
    (docs/distributed.md "Compressed collectives"): named reductions either stay raw
    on the wire (min/max/cat, int dtypes) or quantize under error feedback
    (sum/mean), and sketch merges ship LOSSLESS packed blobs because their callables
    declare the merge contract (``fx.traceable = True`` — a commutative fold over
    stacked states, exact on decoded values). A *plain* callable ``dist_reduce_fx``
    sits outside every one of those lanes: ``process_sync`` ships its state raw, so
    ``SyncOptions(compression="bf16"|"int8")`` quietly buys no bytes for that state —
    and a fork that widened the lossy lane to callables would fold quantization error
    through an arbitrary reducer with no residual to absorb it. The rule warns at the
    ``SyncOptions`` construction site, naming the contract-less reducer.

    Boundary — per-module, like TPU014: a callable is SAFE when the module marks
    ``fx.traceable = True``, imports it from the sketch subsystem, or registers its
    state through ``register_sketch_state``/``kll_spec``/``hist_spec``/
    ``countmin_spec``. Literal ``compression=`` strings only; modes threaded through
    variables or the env knob are out of scope (under-reporting beats noise).
    """
    marked = _tpu018_traceable_names(model.tree)
    sketchy = _tpu018_sketch_imports(model.tree)

    def _owning_class(target: ast.AST) -> Optional[str]:
        for cname, cnode in model.class_nodes.items():
            if any(sub is target for sub in ast.walk(cnode)):
                return cname
        return None

    # (owning class or None, state name, fx display name) — pairing is class-scoped:
    # a lossy SyncOptions in class A must not indict class B's reducer
    unsafe: List[Tuple[Optional[str], str, str]] = []
    for node in ast.walk(model.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr != "add_state":
            continue
        fx: Optional[ast.AST] = node.args[2] if len(node.args) >= 3 else None
        for kw in node.keywords:
            if kw.arg == "dist_reduce_fx":
                fx = kw.value
        if fx is None or (isinstance(fx, ast.Constant) and (fx.value is None or isinstance(fx.value, str))):
            continue  # named reductions and None are codec-safe by construction
        if isinstance(fx, ast.Lambda):
            display = "<lambda>"
        else:
            dotted = _dotted(fx)
            if dotted is None:
                continue
            if dotted[0] in sketchy or dotted[-1] in marked or dotted[0] in marked:
                continue
            display = ".".join(dotted)
        state_name = _const_value(node.args[0]) if node.args else None
        unsafe.append((_owning_class(node), str(state_name), display))
    if not unsafe:
        return []
    out: List[Finding] = []
    for node in ast.walk(model.tree):
        if not isinstance(node, ast.Call) or _final_name(node.func) != "SyncOptions":
            continue
        mode: Optional[str] = None
        for kw in node.keywords:
            if kw.arg == "compression" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if mode not in _TPU018_LOSSY_MODES:
            continue
        site_cls = _owning_class(node)
        relevant = [
            u for u in unsafe
            if site_cls is None or u[0] is None or u[0] == site_cls
        ]
        if not relevant:
            continue
        _cls, state_name, display = relevant[0]
        out.append(_finding(
            "TPU018", path, node, lines,
            f"lossy sync compression {mode!r} configured in a module whose state"
            f" {state_name!r} reduces through callable {display!r} with no"
            " traceable/merge contract: the codec ships that state RAW (no bytes"
            " saved), and a lossy lane over an arbitrary reducer would have no"
            " error-feedback residual to absorb quantization drift — mark the merge"
            " contract (fx.traceable = True), register the state as a sketch, or"
            " keep compression='none' here",
        ))
    return out


# ------------------------------------------------------------------------ TPU019 helpers
#: final call-name segments that count as "the absorption was recorded" — telemetry
#: instruments, flight-ring records, structured logging, warning emission
_TPU019_OBS_CALL_NAMES = {
    "inc", "record", "event", "observe", "bump", "push",
    "warn", "warning", "error", "exception", "critical", "log",
    "capture_bundle", "rank_zero_warn", "_fire",
}
#: dotted-path segments that mark a call as an observability hook regardless of its
#: final name (obs.x(...), telemetry.x(...), flightrec.x(...), logger.x(...))
_TPU019_OBS_MODULES = {"obs", "telemetry", "flightrec", "trace", "bundle", "logger", "logging"}


def _is_seam_file(path: str) -> bool:
    """Modules that ARE the serve/sync/robust seams: any ``serve``/``robust`` directory
    segment, or a ``sync.py`` living under a ``parallel`` directory."""
    parts = path.replace("\\", "/").split("/")
    dirs = parts[:-1]
    if "serve" in dirs or "robust" in dirs:
        return True
    return parts[-1] == "sync.py" and "parallel" in dirs


def _tpu019_broad_type(expr: Optional[ast.AST]) -> Optional[str]:
    """Display name when the except clause is broad (bare / Exception / BaseException,
    alone or inside a tuple); None for narrow handlers."""
    if expr is None:
        return "bare except"
    candidates = list(expr.elts) if isinstance(expr, ast.Tuple) else [expr]
    for cand in candidates:
        name = _final_name(cand)
        if name in ("Exception", "BaseException"):
            return f"except {name}"
    return None


def _tpu019_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler neither re-raises, nor returns a fallback, nor records.

    A ``return`` is a documented-degrade idiom (the caller receives an explicit
    fallback value); a ``raise`` propagates; any observability call — telemetry
    counter/event, flight-ring record, ``rank_zero_warn``, logger — makes the
    absorption visible. Everything else lets execution fall through as if the
    exception never happened: the silent-failure shape this rule exists for.
    """
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return)):
            return False
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if dotted is None:
                continue
            if dotted[-1] in _TPU019_OBS_CALL_NAMES:
                return False
            if any(part in _TPU019_OBS_MODULES for part in dotted[:-1]):
                return False
    return True


def _rule_tpu019(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Silent broad exception swallow on a serve/sync/robust seam function.

    The recovery seams — the async drain, the bounded sync, the journal, the chaos
    harness — are exactly where a swallowed exception costs the most: the engine keeps
    running, the state is quietly wrong or quietly short, and the flight recorder /
    post-mortem bundle that should explain the failure never heard about it
    (docs/observability.md "Flight recorder"). On those modules a broad handler
    (``except:``, ``except Exception:``, ``except BaseException:``) must do at least
    one of: re-raise, ``return`` an explicit fallback value, or record the absorption
    through an observability hook (telemetry counter/event, ``obs.flightrec.record``,
    ``rank_zero_warn``, a logger).

    Boundary: scoped to seam modules (``serve/``/``robust/`` directories and
    ``parallel/sync.py``) — probe-with-fallback handlers elsewhere are out of scope,
    and ``__del__`` is exempt everywhere (GC teardown has no caller to inform and no
    safe hook to call). Narrow handlers (``except OSError:``) stay untouched: catching
    a *named* failure class is a decision; catching everything silently is not.
    """
    if not _is_seam_file(path):
        return []
    out: List[Finding] = []
    for info in model.functions:
        if info.name == "__del__":
            continue
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                broad = _tpu019_broad_type(handler.type)
                if broad is None or not _tpu019_swallows(handler):
                    continue
                out.append(_finding(
                    "TPU019", path, handler, lines,
                    f"{broad} in {info.qualname!r} swallows silently on a"
                    " serve/sync/robust seam: no re-raise, no fallback return, no"
                    " telemetry/flight-ring record — the failure becomes invisible to"
                    " the flight recorder and every post-mortem bundle. Re-raise,"
                    " return an explicit degraded value, or record the absorption"
                    " (obs.flightrec.record / a telemetry counter / rank_zero_warn).",
                ))
    return out


# ------------------------------------------------------------------------ TPU020 helpers
#: process-identity sources: calls whose result names THIS process/host. Distinct from
#: _TPU017_CLOCKS (wall-clock values): an identity read is not merely irreproducible —
#: it is WRONG after any restart, because the compiled program keeps answering with the
#: pid/host of whichever process happened to trace it.
_TPU020_IDENTITY = {
    ("os", "getpid"),
    ("os", "getppid"),
    ("os", "uname"),
    ("socket", "gethostname"),
    ("socket", "getfqdn"),
    ("platform", "node"),
    ("uuid", "uuid1"),
    ("uuid", "uuid4"),
    ("getpass", "getuser"),
    ("telemetry", "process_fingerprint"),
    ("obs", "process_fingerprint"),
}


def _rule_tpu020(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Process-identity read inside jit-traced code.

    Extends TPU017's trace-time-freeze reasoning from clock VALUES to identity LABELS:
    ``os.getpid()`` / ``socket.gethostname()`` / ``uuid.uuid1()`` /
    ``obs.process_fingerprint()`` under ``jax.jit`` executes once, at trace time, and
    the answer is baked into the compiled program. Every telemetry sample, scrape
    label, or incident id derived from it then reports the identity of whichever
    process happened to trace — wrong after a restart (new pid, same cached trace),
    wrong under the persistent compilation cache (a DIFFERENT host's identity can be
    replayed), and silently identical across ranks that share a compiled executable.

    The fleet plane depends on these labels being honest: federation peer
    attribution, per-rank bundle merging, and incident gossip all key on
    ``process_fingerprint()``. The fix is structural, not a retrace: read identity
    once on the eager host path and attach it as labels/metadata OUTSIDE the traced
    computation (exactly how ``obs.openmetrics`` stamps ``tm_process`` info samples).

    Jit-scope only — an identity read on an eager path is correct by construction,
    so there is no hot-path branch here (unlike TPU017).
    """
    out: List[Finding] = []
    for info in model.functions:
        if not info.jit:
            continue
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if dotted is None or len(dotted) < 2 or tuple(dotted[-2:]) not in _TPU020_IDENTITY:
                continue
            if model.is_trace_dead(info, node):
                continue
            ident = ".".join(dotted[-2:])
            out.append(_finding(
                "TPU020", path, node, lines,
                f"process-identity read {ident}() in jit-traced {info.qualname!r}"
                " executes at TRACE time only — the identity is frozen into the"
                " compiled program: stale after a restart, and a persistent"
                " compilation-cache hit can replay another process's identity."
                " Read identity on the eager host path (obs.process_fingerprint())"
                f" and attach it as labels outside the trace{_via_suffix(info.via)}",
            ))
    return out


# ------------------------------------------------------------------------ TPU024 helpers
#: attribute names (leading underscores stripped) whose stores ARE actuator
#: transitions: the serve controller's admission rung and micro-batching dwell
_TPU024_ACTUATORS = {"mode", "mode_idx", "admission_mode", "linger_ms", "coalesce", "dwell"}
#: constructors build the INITIAL actuator position — that is configuration, not a
#: transition, so no flight event is owed there
_TPU024_EXEMPT = {"__init__", "__post_init__", "__new__"}


def _tpu024_emits_flight_event(info: "_FuncInfo") -> bool:
    """Does this function call the flight recorder (``record``/``open_incident``)?

    Matches ``flightrec.record(...)`` / ``_flightrec.record(...)`` /
    ``obs.flightrec.open_incident(...)`` and bare ``record(...)`` (the from-import
    form). A chained ``telemetry.series(...).record(...)`` is NOT a match — the call
    chain is not a pure name path, so ``_dotted`` already rejects it.
    """
    for node in _scoped_walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted is None or dotted[-1] not in ("record", "open_incident"):
            continue
        if len(dotted) == 1 or any(p in ("flightrec", "_flightrec") for p in dotted[:-1]):
            return True
    return False


def _rule_tpu024(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """Actuator state transition without a flight-recorder emission in the function.

    The adaptive serving loop's whole determinism/observability story
    (docs/serving.md "Control loop") rests on one invariant: every actuator movement
    — an admission-ladder rung change, a linger/coalesce dwell change — is visible,
    both as a ``control.*`` flight event carrying the triggering signal values and as
    a decision-journal record. A code path that mutates an actuator field without
    recording breaks replay auditability silently: the journal says one history, the
    live engine ran another, and the first place anyone notices is a bit-identity
    failure in a post-mortem.

    Structurally: on a seam module (``serve/``/``robust/``), any function that stores
    to an actuator-named attribute (``mode``/``mode_idx``/``admission_mode``/
    ``linger_ms``/``coalesce``/``dwell``, underscore-insensitive) must also call the
    flight recorder (``flightrec.record``/``open_incident``) somewhere in the SAME
    function — the mutate-and-record seam pattern ``ServeController._transition``
    models. Constructors are exempt (the initial position is configuration, not a
    transition).
    """
    if not _is_seam_file(path):
        return []
    out: List[Finding] = []
    for info in model.functions:
        if info.name in _TPU024_EXEMPT:
            continue
        stores: List[ast.Attribute] = []
        for node in _scoped_walk(info.node):
            if isinstance(node, ast.Assign):
                targets: List[ast.AST] = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            else:
                continue
            for target in targets:
                elts = target.elts if isinstance(target, ast.Tuple) else [target]
                for el in elts:
                    if (
                        isinstance(el, ast.Attribute)
                        and el.attr.lstrip("_") in _TPU024_ACTUATORS
                    ):
                        stores.append(el)
        if not stores or _tpu024_emits_flight_event(info):
            continue
        for el in stores:
            out.append(_finding(
                "TPU024", path, el, lines,
                f"actuator transition ({el.attr!r} store) in {info.qualname!r} with no"
                " flight-recorder emission in the same function: the control event"
                " stream (and with it the decision journal and adaptive replay"
                " bit-identity) goes silently incomplete. Route the mutation through"
                " a seam that also calls flightrec.record('control.decision', ...)"
                " with the triggering signal values.",
            ))
    return out


#: jit-wrapper constructors whose result carries a per-object compilation cache: a fresh
#: call builds a fresh cache, so constructing one per invocation retraces per invocation
_TPU025_JIT_WRAPPERS = {"jit", "pjit", "filter_jit"}


def _rule_tpu025(model: _ModuleModel, lines: Sequence[str], path: str) -> List[Finding]:
    """``jit`` applied to a lambda/locally-def'd closure rebuilt on every call.

    ``jax.jit`` keys its compilation cache on the *wrapped callable's identity*: a
    lambda or a ``def`` nested in the enclosing function is a NEW object each time the
    enclosing function runs, so the jit wrapper built around it starts with an empty
    cache and retraces — and XLA recompiles — on every single invocation. Nothing
    crashes; the run is just quietly 10-1000x slower, and only the compile plane
    (``compile.count`` climbing linearly with steps, no attributable culprit because
    every trace IS a first trace) gives it away at runtime. This rule catches the
    pattern statically, at the construction site.

    Structurally: inside any function body, a call whose target's final name is
    ``jit``/``pjit``/``filter_jit`` with a first argument that is a ``lambda``
    expression or a bare name bound to a function def'd in the SAME enclosing scope,
    in one of the two shapes where the per-call rebuild is unambiguous:

    - **immediately invoked** — ``jax.jit(kernel)(state, batch)``: nothing retains
      the wrapper, so every execution of the line rebuilds it from scratch;
    - **constructed inside a loop body** — the wrapper is rebuilt per iteration.

    A wrapper that is merely *assigned* and reused (``run_j = jax.jit(run)`` followed
    by a timing loop over ``run_j`` — the build-once-then-drive benchmark idiom, or
    the engine's memoised ``_jit_cache`` stores) amortises its one trace and is given
    the benefit of the doubt; if such a site DOES churn at runtime the compile plane
    names it anyway. Module-scope ``jit(lambda ...)`` is exempt — built once at
    import, its cache lives as long as the module.
    """
    out: List[Finding] = []
    for info in model.functions:
        local_defs = {child.name for child in info.children}
        # the two unambiguous shapes: jit(...) used as the callee of another call,
        # and jit(...) constructed inside a loop body within this scope
        invoked: Set[int] = set()
        in_loop: Set[int] = set()
        memoised: Set[int] = set()  # jit calls stored into a subscript/attribute
        for node in _scoped_walk(info.node):
            if isinstance(node, ast.Call):
                invoked.add(id(node.func))
            elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                in_loop.update(id(sub) for sub in _scoped_walk(node))
            elif isinstance(node, ast.Assign) and any(
                isinstance(t, (ast.Subscript, ast.Attribute)) for t in node.targets
            ):
                memoised.add(id(node.value))
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            dotted = _dotted(node.func)
            if dotted is None or dotted[-1] not in _TPU025_JIT_WRAPPERS:
                continue
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                what = "a lambda"
            elif isinstance(target, ast.Name) and target.id in local_defs:
                what = f"locally-def'd closure {target.id!r}"
            else:
                continue
            if id(node) in invoked:
                shape = "immediately invoked"
            elif id(node) in in_loop and id(node) not in memoised:
                shape = "constructed inside a loop body"
            else:
                continue
            wrapper = ".".join(dotted)
            out.append(_finding(
                "TPU025", path, node, lines,
                f"{wrapper}(...) applied to {what} inside {info.qualname!r} and"
                f" {shape}: the wrapped callable (and therefore the jit wrapper's"
                " compilation cache) is rebuilt on every call, so the kernel"
                " retraces — and XLA recompiles — per invocation. Hoist the"
                " function to module/class scope or build the wrapper once and"
                " cache it (the engine's _jit_cache pattern); obs.xplane's compile"
                " ledger shows this churn at runtime as compile.count climbing"
                " linearly with steps.",
            ))
    return out


_RULE_FUNCS = (
    _rule_tpu001, _rule_tpu002, _rule_tpu003, _rule_tpu004, _rule_tpu005, _rule_tpu006,
    _rule_tpu007, _rule_tpu008, _rule_tpu009, _rule_tpu010, _rule_tpu011, _rule_tpu012,
    _rule_tpu013, _rule_tpu014, _rule_tpu015, _rule_tpu016, _rule_tpu017, _rule_tpu018,
    _rule_tpu019, _rule_tpu020, _rule_tpu024, _rule_tpu025,
)


def run_rules(
    tree: ast.Module,
    lines: Sequence[str],
    path: str,
    model: Optional[_ModuleModel] = None,
) -> List[Finding]:
    """Run every registered rule over one parsed module.

    ``model`` lets the whole-program pass (project.py) hand in a module model it already
    built — and decorated with interprocedural marks — instead of re-inferring from the
    bare tree.
    """
    if model is None:
        model = _ModuleModel(tree)
    findings: List[Finding] = []
    for rule in _RULE_FUNCS:
        findings.extend(rule(model, lines, path))
    return findings
