"""jaxlint baseline: waived legacy findings, checked in next to the analyzer.

A baseline entry waives findings by ``(rule, path, fingerprint)`` — the fingerprint is the
whitespace-normalised source line, NOT the line number, so edits elsewhere in a file never
invalidate the baseline. ``count`` waives up to that many identical findings per key
(several structurally-identical hazards can share one normalised line).

Workflow::

    python -m torchmetrics_tpu._lint torchmetrics_tpu           # gate: new findings fail
    python -m torchmetrics_tpu._lint torchmetrics_tpu --write-baseline   # re-waive current set

Stale entries (baselined findings that no longer occur) are reported on every run and fail
the gate under ``--strict-baseline`` (the ``make jaxlint`` mode), so the waived set can only
shrink silently, never rot.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Sequence, Tuple

from torchmetrics_tpu._lint.core import Finding

#: The baseline shipped with the package (valid for source checkouts and installs alike).
DEFAULT_BASELINE_PATH = Path(__file__).with_name("baseline.json")

_Key = Tuple[str, str, str]


def _keyed(findings: Sequence[Finding]) -> Dict[_Key, List[Finding]]:
    keyed: Dict[_Key, List[Finding]] = {}
    for f in findings:
        keyed.setdefault(f.key, []).append(f)
    return keyed


def write_baseline(findings: Sequence[Finding], path: Any) -> Dict[str, Any]:
    """Serialise the current finding set as the new baseline; returns the written payload."""
    entries = []
    for (rule, fpath, fingerprint), group in sorted(_keyed(findings).items()):
        entries.append(
            {
                "rule": rule,
                "path": fpath,
                "fingerprint": fingerprint,
                "count": len(group),
                "lines": [f.line for f in group],  # informational only — never matched on
            }
        )
    payload = {"version": 1, "tool": "jaxlint", "entries": entries}
    Path(path).write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")
    return payload


def load_baseline(path: Any) -> List[Dict[str, Any]]:
    """Baseline entries from ``path``; empty list when the file does not exist."""
    p = Path(path)
    if not p.exists():
        return []
    payload = json.loads(p.read_text(encoding="utf-8"))
    if not isinstance(payload, dict) or payload.get("tool") != "jaxlint":
        raise ValueError(f"{p}: not a jaxlint baseline file")
    return list(payload.get("entries", []))


def apply_baseline(
    findings: Sequence[Finding], entries: Sequence[Dict[str, Any]]
) -> Tuple[List[Finding], int, List[Dict[str, Any]]]:
    """Split findings into (new, waived_count, stale_entries) against baseline entries.

    Per key, ``min(current, baselined)`` findings are waived; current findings beyond the
    baselined count are new; baseline capacity beyond the current count marks the entry stale
    (its ``count`` is adjusted in the returned stale record for partial staleness).
    """
    remaining: Dict[_Key, int] = {}
    for e in entries:
        key = (e["rule"], e["path"], e["fingerprint"])
        remaining[key] = remaining.get(key, 0) + int(e.get("count", 1))
    new: List[Finding] = []
    waived = 0
    for f in findings:
        if remaining.get(f.key, 0) > 0:
            remaining[f.key] -= 1
            waived += 1
        else:
            new.append(f)
    stale = [
        {"rule": k[0], "path": k[1], "fingerprint": k[2], "count": n}
        for k, n in sorted(remaining.items())
        if n > 0
    ]
    return new, waived, stale
