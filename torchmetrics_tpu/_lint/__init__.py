"""torchmetrics_tpu._lint — **jaxlint**, the AST-based JAX/TPU hazard analyzer.

Static twin of the runtime ``obs`` telemetry: hazards that ``obs`` counts when a program
executes (retrace churn, host syncs, dispatch storms) are visible in the source long before
any accelerator runs — this package flags them at lint time, with a checked-in baseline so
CI gates only on *new* findings. Stdlib-only: importing or running the analyzer never
initialises jax or touches a device.

Usage::

    python -m torchmetrics_tpu._lint torchmetrics_tpu            # lint the package
    make jaxlint                                                 # CI gate (strict baseline)

Rules TPU000–TPU023 are documented with bad/good examples in ``docs/static-analysis.md``
(the catalog table there is generated from ``rules.RULE_META``); per-line suppression is
``# jaxlint: disable=TPU00X``. The default run is whole-program (``_lint/project.py``):
interprocedural jit/donation/hot-path marks propagate across module boundaries, findings
carry a ``via:`` call path, and the concurrency pass (``_lint/concurrency.py``, rules
TPU021–TPU023) runs thread-root discovery + lockset dataflow over the same call graph —
its dynamic half is the seeded schedule explorer ``_lint/racerun.py``
(``make jaxlint-race``). The opt-in jaxpr IR backend (``--ir``, ``_lint/irlint.py``) and
the racerun harness scenarios are the only components that import jax.
"""
from torchmetrics_tpu._lint.baseline import (
    DEFAULT_BASELINE_PATH,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from torchmetrics_tpu._lint.core import Finding, analyze_paths, analyze_source
from torchmetrics_tpu._lint.rules import RULES

__all__ = [
    "DEFAULT_BASELINE_PATH",
    "Finding",
    "RULES",
    "analyze_paths",
    "analyze_source",
    "apply_baseline",
    "load_baseline",
    "package_lint_status",
    "write_baseline",
]


def package_lint_status() -> dict:
    """One-shot analyzer status over the installed package, against the shipped baseline.

    Returns ``{"findings", "new", "baselined", "stale"}`` counts. Cached after the first
    call (the tree is re-parsed only once per process) — cheap enough for
    ``obs.bench_extras()`` to embed in every BENCH JSON.
    """
    global _STATUS_CACHE
    if _STATUS_CACHE is None:
        import os
        from pathlib import Path

        from torchmetrics_tpu._lint.cache import DEFAULT_CACHE_PATH, ENV_CACHE_PATH, LintCache
        from torchmetrics_tpu._lint.core import LAST_RUN_STATS

        package_root = Path(__file__).resolve().parent.parent
        cache = LintCache(os.environ.get(ENV_CACHE_PATH, DEFAULT_CACHE_PATH))
        findings = analyze_paths([package_root], cache=cache)
        new, waived, stale = apply_baseline(findings, load_baseline(DEFAULT_BASELINE_PATH))
        _STATUS_CACHE = {
            "findings": len(findings),
            "new": len(new),
            "baselined": waived,
            "stale": len(stale),
            "runtime_ms": LAST_RUN_STATS.get("runtime_ms"),
            "cache_hits": LAST_RUN_STATS.get("cache_hits", 0),
            "cache_misses": LAST_RUN_STATS.get("cache_misses", 0),
        }
    return dict(_STATUS_CACHE)


_STATUS_CACHE = None
