"""tmrace: whole-program concurrency analysis for the serving/observability thread plane.

The reference library is single-threaded by construction; this repro is not. The PR 11
drain thread, the scrape/federation server threads (one per in-flight HTTP request —
``ThreadingHTTPServer``), the bounded-gather worker, and ``atexit`` close hooks all
mutate state the main thread also touches, governed so far by conventions (the engine's
single-mutator contract, quiesce-on-every-host-access) that only example-based tests
defend. This module gives those contracts the same treatment jaxlint gave the
jit/donation contracts: a static pass over PR 9's project-wide call graph.

Three layers, three rules:

1. **Thread-root discovery.** A *root* is an entry point the Python runtime can drive
   concurrently with the main thread: ``threading.Thread(target=f)`` targets,
   ``ThreadingHTTPServer``/``HTTPServer`` handler-class methods (self-concurrent — the
   server spawns one thread per request), ``atexit.register(f)`` hooks, and defs marked
   ``# jaxlint: thread-root``. The implicit ``main`` root seeds every public function
   (user code calls the API from the main thread); reachability per root is the closure
   of the resolved call graph. ``main`` and ``atexit`` are the SAME OS thread (exit
   hooks run on the main thread at interpreter shutdown), so they are never concurrent
   with each other — only with real thread/handler roots.

2. **Lockset dataflow.** ``with lock:`` regions and ``acquire()``–``release()`` spans
   yield the set of locks held at every statement; a callee's *entry lockset* is the
   meet (intersection) over all reachable call sites, iterated to fixpoint — so a
   helper only ever invoked under ``self._cond`` analyzes as holding it
   (``_ensure_drain_locked``), while a helper reachable both locked and unlocked
   analyzes as holding nothing.

3. **The rules.**

   - **TPU021** — an attribute/global written from ≥2 mutually-concurrent roots with
     disjoint locksets. GIL-atomic container ops (``append``/``appendleft``/``popleft``
     /``pop``/``add``/``discard``) are sanctioned — the lock-light rings are a design,
     not a race — as are fields whose write (or ``__init__`` default) line carries
     ``# jaxlint: single-mutator`` (the engine's quiesce-barrier protocol: exactly one
     mutator at a time, enforced dynamically, justified by a passing
     ``racerun`` schedule).
   - **TPU022** — a public host-access entry point of an engine-attachable class (one
     that assigns ``self._serve``) touches tensor state without routing through the
     quiesce seam. This is the docs/serving.md "every host access quiesces first"
     table, checked structurally instead of by enumeration.
   - **TPU023** — check-then-act: an ``if``/``while`` test (or a multi-step read —
     iteration such as ``.items()``/``.values()``/``for``) of a shared field outside
     the lock that consistently guards that field's writes on a concurrent root.
     Single attribute loads are NOT flagged (a one-word read is GIL-atomic); the races
     worth reporting are decisions taken on stale state (``if self._closed:`` vs a
     concurrent ``close()``) and iterations a concurrent resize can explode.

Per-module analysis (``analyze_source`` / ``--no-project``) cannot see thread roots in
other files, so these rules run ONLY in the whole-program pass — mirroring how
interprocedural marks already work. Under-reporting beats noise throughout: writes
through unresolvable objects, fields of classes never reached from a non-main root,
and ``__init__``-time stores (the object has not escaped yet) are all out of scope.

The dynamic half lives in :mod:`torchmetrics_tpu._lint.racerun`: every TPU021 finding
is either reproduced into a failing deterministic schedule or sanctioned by a marker
whose named scenario passes all explored interleavings (``make jaxlint-race``).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from torchmetrics_tpu._lint.core import Finding
from torchmetrics_tpu._lint.rules import (
    _dotted,
    _final_name,
    _finding,
    _FuncInfo,
    _scoped_walk,
)

#: def-line marker declaring a function a thread entry point the discovery cannot see
#: (e.g. a callback handed to an external scheduler)
_THREAD_ROOT_RE = re.compile(r"#\s*jaxlint:\s*thread-root\b")
#: write-site / field-default marker: the field is protected by a single-mutator
#: protocol (quiesce barrier / sole-writer thread), not a lock — every use must name
#: the racerun scenario that justifies it, as a trailing comment of the form
#: "jaxlint: single-mutator (racerun: engine_enqueue_vs_quiesce)"
_SINGLE_MUTATOR_RE = re.compile(r"#\s*jaxlint:\s*single-mutator\b(?:\s*\(racerun:\s*(?P<scenario>[\w.-]+)\))?")

#: constructors whose result is a lock object (``threading.`` prefix or bare import)
_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"})
#: with-target name heuristic: ``with self._poll_mutex:`` guards even if the ctor
#: assignment lives outside the analyzed tree
_LOCKISH_NAME_RE = re.compile(r"(?:^|_)(?:lock|cond|mutex|guard)$")

#: container mutators that are a single bytecode-visible C call under the GIL — the
#: sanctioned "deque/ring append" tier of the lock-light rings
_ATOMIC_MUTATORS = frozenset({"append", "appendleft", "popleft", "pop", "add", "discard", "clear"})
#: non-atomic (multi-step / resizing) mutating method names treated as writes
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "popleft", "pop", "clear",
    "update", "add", "remove", "discard", "setdefault", "insert", "set",
})
#: read-side method names that take multiple steps over the container (iteration /
#: snapshotting) — the TPU023 "multi-step read" tier
_ITER_READS = frozenset({"items", "values", "keys", "copy"})

#: server classes whose second positional argument is a per-request handler class
_HANDLER_SERVERS = frozenset({"ThreadingHTTPServer", "HTTPServer", "TCPServer", "ThreadingTCPServer"})

#: ``self._state`` sub-attributes that ARE tensor state (TPU022's "touches tensor
#: state"); ``.generation`` deliberately absent — fence readers poll it lock-free
_STATE_TENSOR_ATTRS = frozenset({"tensors", "lists", "snapshot", "restore", "values"})


class _Root:
    """One concurrent entry point. ``main`` and ``atexit`` share the main OS thread."""

    __slots__ = ("kind", "label", "self_concurrent")

    def __init__(self, kind: str, label: str, self_concurrent: bool = False) -> None:
        self.kind = kind  # main | thread | handler | atexit | mark
        self.label = label
        self.self_concurrent = self_concurrent

    def concurrent_with(self, other: "_Root") -> bool:
        if self is other:
            return self.self_concurrent
        if self.kind in ("main", "atexit") and other.kind in ("main", "atexit"):
            return False  # exit hooks run on the main thread
        return True

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"_Root({self.kind}:{self.label})"


class _Access:
    """One shared-field access with its location, lockset, and root provenance."""

    __slots__ = ("field", "kind", "path", "node", "lockset", "func", "atomic", "sanction", "in_test")

    def __init__(self, field, kind, path, node, lockset, func, atomic=False, sanction=None, in_test=False):
        self.field = field          # (path, class-or-scope, attr)
        self.kind = kind            # "write" | "read"
        self.path = path
        self.node = node
        self.lockset: FrozenSet[str] = lockset
        self.func = func            # _FuncInfo
        self.atomic = atomic        # GIL-atomic container op
        self.sanction = sanction    # "single-mutator" | None
        self.in_test = in_test      # read inside an if/while test (check-then-act)


class ConcurrencyModel:
    """Thread roots, per-root reachability, and entry locksets over a ProjectModel."""

    def __init__(self, pm) -> None:
        self.pm = pm
        self.roots: List[_Root] = [_Root("main", "main thread")]
        #: id(_FuncInfo) -> set of root indices that can reach it
        self.roots_of: Dict[int, Set[int]] = {}
        #: id(_FuncInfo) -> meet of locksets over reachable call sites (entry lockset)
        self.entry_lockset: Dict[int, FrozenSet[str]] = {}
        self._func_entry: Dict[int, Tuple] = {}  # id(info) -> (entry, info)
        self._class_locks: Dict[Tuple[str, str], Set[str]] = {}  # (path, cls) -> attrs
        self._module_locks: Dict[str, Set[str]] = {}             # path -> names
        self._module_globals: Dict[str, Set[str]] = {}           # path -> module-scope names
        self._instance_of: Dict[Tuple[str, str], str] = {}       # (path, name) -> class
        self._bound_methods: Dict[Tuple[str, str], Tuple[str, str]] = {}  # (path, name) -> (cls, meth)
        self._root_entries: Set[int] = set()  # id(info) for every non-main root entry
        self._root_keys: Set[Tuple] = set()   # dedup: same call seen from two scans
        #: (path, cls) whose instances can be reached from more than one thread: bound
        #: to a module global, stored into another object's attribute, or spawning a
        #: thread on their own method. Fields of UN-anchored classes (e.g. a per-request
        #: render helper built and dropped inside one function) are thread-local.
        self._shared_classes: Set[Tuple[str, str]] = set()
        #: id(info) -> [(resolved target infos, LOCAL lockset at the call site)]:
        #: call-edge structure is sweep-invariant, so the body walk + resolution run
        #: once per function and the fixpoint only re-does the cheap set algebra
        self._edges: Dict[int, List[Tuple[List, FrozenSet[str]]]] = {}
        for entry in pm.entries:
            for info in entry.model.functions:
                self._func_entry[id(info)] = (entry, info)
        self._collect_module_facts()
        self._discover_roots()
        self._seed_and_propagate()

    # ------------------------------------------------------------------- module facts
    def _collect_module_facts(self) -> None:
        for entry in self.pm.entries:
            mlocks: Set[str] = set()
            mglobals: Set[str] = set()
            for node in entry.tree.body:
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, ast.AnnAssign) and node.target is not None:
                    targets = [node.target]
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    mglobals.add(t.id)
                    value = node.value
                    if value is None:
                        continue
                    if self._is_lock_ctor(value):
                        mlocks.add(t.id)
                    elif isinstance(value, ast.Call):
                        cname = _final_name(value.func)
                        if cname in entry.model.class_nodes:
                            self._instance_of[(entry.path, t.id)] = cname
                    d = _dotted(value)
                    if d is not None and len(d) == 2 and (entry.path, d[0]) in self._instance_of:
                        # ``record = recorder.record`` — a module-level bound method
                        cls = self._instance_of[(entry.path, d[0])]
                        self._bound_methods[(entry.path, t.id)] = (cls, d[1])
            self._module_locks[entry.path] = mlocks
            self._module_globals[entry.path] = mglobals
            for info in entry.model.functions:
                if info.cls is None:
                    continue
                for node in _scoped_walk(info.node):
                    if isinstance(node, ast.Assign) and self._is_lock_ctor(node.value):
                        for t in node.targets:
                            d = _dotted(t)
                            if d and len(d) == 2 and d[0] == "self":
                                self._class_locks.setdefault((entry.path, info.cls), set()).add(d[1])
            # function-local locks (closure guards like federation's ``poll_lock``)
            for info in entry.model.functions:
                for node in _scoped_walk(info.node):
                    if isinstance(node, ast.Assign) and self._is_lock_ctor(node.value):
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self._module_locks[entry.path].add(t.id)
            # shared-class anchors (see _shared_classes)
            for name, cname in list(self._instance_of.items()):
                if name[0] == entry.path:
                    self._shared_classes.add((entry.path, cname))
            for info in entry.model.functions:
                fglobals = {
                    n for node in _scoped_walk(info.node) if isinstance(node, ast.Global)
                    for n in node.names
                }
                for node in _scoped_walk(info.node):
                    if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                        cname = _final_name(node.value.func)
                        owners = []
                        if cname in entry.model.class_nodes:
                            owners.append((entry.path, cname))
                        imp = entry.imports.get(cname or "")
                        if imp is not None:
                            towner = self.pm.by_module.get(imp[0])
                            if towner is not None and imp[1] in towner.model.class_nodes:
                                owners.append((towner.path, imp[1]))
                        if not owners:
                            continue
                        for t in node.targets:
                            d = _dotted(t)
                            if (d and d[0] == "self" and len(d) == 2) or (
                                isinstance(t, ast.Name) and t.id in fglobals
                            ):
                                self._shared_classes.update(owners)
                    if (info.cls is not None and isinstance(node, ast.Call)
                            and _final_name(node.func) == "Thread"):
                        self._shared_classes.add((entry.path, info.cls))

    @staticmethod
    def _is_lock_ctor(value: Optional[ast.AST]) -> bool:
        return isinstance(value, ast.Call) and _final_name(value.func) in _LOCK_CTORS

    def _lock_key(self, entry, info: Optional[_FuncInfo], expr: ast.AST) -> Optional[str]:
        """Lock identity of a ``with``-context / acquire-release expression, or None."""
        d = _dotted(expr)
        if d is None:
            return None
        name = d[-1]
        if d[0] == "self" and info is not None and info.cls is not None and len(d) >= 2:
            attr = d[1]
            if attr in self._class_locks.get((entry.path, info.cls), ()) or _LOCKISH_NAME_RE.search(attr):
                return f"{entry.path}::{info.cls}.{attr}"
            return None
        if len(d) == 1:
            if d[0] in self._module_locks.get(entry.path, ()) or _LOCKISH_NAME_RE.search(d[0]):
                return f"{entry.path}::{d[0]}"
            return None
        # dotted non-self chain (module-global lock via alias, etc.)
        if _LOCKISH_NAME_RE.search(name) or name in self._module_locks.get(entry.path, ()):
            return ".".join(d)
        return None

    # -------------------------------------------------------------------- thread roots
    def _marked_thread_root(self, entry, info: _FuncInfo) -> bool:
        dl = info.node.lineno
        src = entry.lines[dl - 1] if 0 < dl <= len(entry.lines) else ""
        return bool(_THREAD_ROOT_RE.search(src))

    def _discover_roots(self) -> None:
        for entry in self.pm.entries:
            # per-function scan so Thread(target=self._x) resolves against the class
            for info in entry.model.functions:
                if self._marked_thread_root(entry, info):
                    self._add_root("mark", entry, [info], f"marked thread-root {entry.path}::{info.qualname}")
                for node in _scoped_walk(info.node):
                    if isinstance(node, ast.Call):
                        self._root_from_call(entry, info, node)
            for node in ast.walk(entry.tree):  # module-scope Thread(...)/atexit hooks
                if isinstance(node, ast.Call):
                    self._root_from_call(entry, None, node)

    def _root_from_call(self, entry, info: Optional[_FuncInfo], call: ast.Call) -> None:
        fname = _final_name(call.func)
        if fname == "Thread":
            target = next((kw.value for kw in call.keywords if kw.arg == "target"), None)
            if target is not None:
                funcs = self._resolve_ref(entry, info, target)
                if funcs:
                    label = f"thread {entry.path}::{funcs[0][1].qualname}"
                    for kw in call.keywords:
                        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                            label = f"thread '{kw.value.value}'"
                    self._add_root("thread", entry, [fi for _, fi in funcs], label)
        elif fname in _HANDLER_SERVERS and len(call.args) >= 2:
            hname = _final_name(call.args[1])
            if hname and hname in entry.model.class_nodes:
                methods = [fi for fi in entry.model.functions if fi.cls == hname]
                if methods:
                    self._add_root("handler", entry, methods,
                                   f"HTTP handler {entry.path}::{hname}", self_concurrent=True)
        elif fname == "register":
            d = _dotted(call.func)
            if d and d[0] == "atexit" and call.args:
                funcs = self._resolve_ref(entry, info, call.args[0])
                if funcs:
                    self._add_root("atexit", entry, [fi for _, fi in funcs],
                                   f"atexit hook {entry.path}::{funcs[0][1].qualname}")

    def _resolve_ref(self, entry, info: Optional[_FuncInfo], expr: ast.AST) -> List[Tuple]:
        """Resolve a function REFERENCE (not a call): ``self._m``, a bare name, ``mod.f``."""
        d = _dotted(expr)
        if d is None:
            return []
        if d[0] == "self" and len(d) == 2 and info is not None and info.cls is not None:
            return [(entry, fi) for fi in entry.model.by_name.get(d[1], []) if fi.cls == info.cls]
        if len(d) == 1:
            tgt = entry.imports.get(d[0])
            if tgt is not None:
                return self.pm._lookup(*tgt)
            return [(entry, fi) for fi in entry.model.by_name.get(d[0], [])]
        head = entry.module_aliases.get(d[0])
        if head is not None and len(d) == 2:
            return self.pm._lookup(head, d[1])
        return []

    def _add_root(self, kind: str, entry, funcs: Sequence[_FuncInfo], label: str,
                  self_concurrent: bool = False) -> None:
        key = (kind, label, frozenset(id(fi) for fi in funcs))
        if key in self._root_keys:
            return  # the module-scope scan re-visits calls inside function bodies
        self._root_keys.add(key)
        idx = len(self.roots)
        self.roots.append(_Root(kind, label, self_concurrent))
        for fi in funcs:
            self._root_entries.add(id(fi))
            self.roots_of.setdefault(id(fi), set()).add(idx)
            self._meet_entry(fi, frozenset())

    # -------------------------------------------------------- reachability + locksets
    def _meet_entry(self, info: _FuncInfo, lockset: FrozenSet[str]) -> bool:
        have = self.entry_lockset.get(id(info))
        new = lockset if have is None else (have & lockset)
        if new != have:
            self.entry_lockset[id(info)] = new
            return True
        return False

    def _seed_and_propagate(self) -> None:
        main = 0
        for entry in self.pm.entries:
            for info in entry.model.functions:
                if id(info) in self._root_entries:
                    continue
                public = not info.name.startswith("_") or info.name in (
                    "__init__", "__call__", "__enter__", "__exit__", "__len__",
                )
                if public:
                    self.roots_of.setdefault(id(info), set()).add(main)
                    self._meet_entry(info, frozenset())
        # fixpoint: roots and entry locksets flow along resolved call edges
        for _ in range(64):
            if not self._sweep():
                break

    def _call_edges(self, entry, info: _FuncInfo) -> List[Tuple[List, FrozenSet[str]]]:
        """Resolved call edges of one function with their call-site-local locksets.

        Cached: the walk and the resolution are sweep-invariant. The cached lockset is
        computed from an EMPTY base; the sweep unions the (shrinking) entry lockset
        back in, which matches the walker exactly except for the degenerate case of a
        function releasing a lock it never acquired — there the union over-approximates
        and the meet stays conservative-by-locks, never inventing a new race.
        """
        edges = self._edges.get(id(info))
        if edges is None:
            edges = []
            for node, lockset in self._walk_locked(entry, info, frozenset()):
                if not isinstance(node, ast.Call):
                    continue
                targets = [
                    tinfo for _te, tinfo in self._resolve_call(entry, info, node)
                    if tinfo is not info
                ]
                if targets:
                    edges.append((targets, lockset))
            self._edges[id(info)] = edges
        return edges

    def _sweep(self) -> bool:
        changed = False
        for entry in self.pm.entries:
            for info in entry.model.functions:
                roots = self.roots_of.get(id(info))
                if not roots:
                    continue
                base = self.entry_lockset.get(id(info), frozenset())
                for targets, local in self._call_edges(entry, info):
                    lockset = base | local
                    for tinfo in targets:
                        have = self.roots_of.setdefault(id(tinfo), set())
                        if not roots <= have:
                            have |= roots
                            changed = True
                        if self._meet_entry(tinfo, lockset):
                            changed = True
        return changed

    def _resolve_call(self, entry, info: _FuncInfo, call: ast.Call) -> List[Tuple]:
        targets = self.pm.resolve_call(entry, info, call)
        if targets:
            return targets
        fn = call.func
        d = _dotted(fn)
        if isinstance(fn, ast.Name):
            # closure / cross-class same-module fallback (resolve_call's class filter
            # hides nested defs like a handler calling its server's local helper)
            return [(entry, fi) for fi in entry.model.by_name.get(fn.id, [])]
        if d is None:
            return []
        name = d[-1]
        # module-level bound methods (``flightrec.record`` == ``recorder.record``) and
        # module-level instances (``ring.push`` -> TraceRing.push)
        if len(d) >= 2:
            head_entry, sym = entry, d[0]
            alias = entry.module_aliases.get(d[0])
            if alias is not None and len(d) == 2:
                tentry = self.pm.by_module.get(alias)
                if tentry is not None:
                    bm = self._bound_methods.get((tentry.path, d[1]))
                    if bm is not None:
                        cls, meth = bm
                        return [(tentry, fi) for fi in tentry.model.by_name.get(meth, []) if fi.cls == cls]
            if len(d) == 3 and alias is not None:
                tentry = self.pm.by_module.get(alias)
                if tentry is not None and (tentry.path, d[1]) in self._instance_of:
                    cls = self._instance_of[(tentry.path, d[1])]
                    return [(tentry, fi) for fi in tentry.model.by_name.get(name, []) if fi.cls == cls]
            inst = self._instance_of.get((head_entry.path, sym))
            if inst is not None and len(d) == 2:
                return [(entry, fi) for fi in entry.model.by_name.get(name, []) if fi.cls == inst]
            imp = entry.imports.get(sym)
            if imp is not None and len(d) == 2:
                tentry = self.pm.by_module.get(imp[0])
                if tentry is not None:
                    inst = self._instance_of.get((tentry.path, imp[1]))
                    if inst is not None:
                        return [(tentry, fi) for fi in tentry.model.by_name.get(name, []) if fi.cls == inst]
        # duck-typed same-module fallback: ``fed.poll()`` links to Federator.poll when
        # federation.py defines exactly that method — conservative, module-scoped
        cands = [fi for fi in entry.model.by_name.get(name, []) if fi.cls is not None]
        return [(entry, fi) for fi in cands]

    # The lockset walker: yields (node, frozen lockset) for every node in the body,
    # tracking ``with lock:`` scopes and acquire()/release() spans, skipping nested
    # function/class scopes (they are analyzed as their own functions).
    def _walk_locked(self, entry, info: _FuncInfo, base: FrozenSet[str]
                     ) -> Iterator[Tuple[ast.AST, FrozenSet[str]]]:
        body = getattr(info.node, "body", [])
        yield from self._walk_stmts(entry, info, body, set(base))

    def _acq_rel_key(self, entry, info, stmt: ast.AST, which: str) -> Optional[str]:
        if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
            return None
        fn = stmt.value.func
        if _final_name(fn) != which or not isinstance(fn, ast.Attribute):
            return None
        return self._lock_key(entry, info, fn.value)

    def _walk_stmts(self, entry, info, body: Sequence[ast.stmt], held: Set[str]
                    ) -> Iterator[Tuple[ast.AST, FrozenSet[str]]]:
        for stmt in body:
            ak = self._acq_rel_key(entry, info, stmt, "acquire")
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = set(held)
                for item in stmt.items:
                    yield from self._walk_expr(item.context_expr, held)
                    k = self._lock_key(entry, info, item.context_expr)
                    if k:
                        inner.add(k)
                yield (stmt, frozenset(held))
                yield from self._walk_stmts(entry, info, stmt.body, inner)
            elif isinstance(stmt, (ast.If, ast.While)):
                yield (stmt, frozenset(held))
                yield from self._walk_expr(stmt.test, held)
                yield from self._walk_stmts(entry, info, stmt.body, set(held))
                yield from self._walk_stmts(entry, info, stmt.orelse, set(held))
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                yield (stmt, frozenset(held))
                yield from self._walk_expr(stmt.iter, held)
                yield from self._walk_expr(stmt.target, held)
                yield from self._walk_stmts(entry, info, stmt.body, set(held))
                yield from self._walk_stmts(entry, info, stmt.orelse, set(held))
            elif isinstance(stmt, ast.Try):
                yield (stmt, frozenset(held))
                yield from self._walk_stmts(entry, info, stmt.body, set(held))
                for h in stmt.handlers:
                    yield from self._walk_stmts(entry, info, h.body, set(held))
                yield from self._walk_stmts(entry, info, stmt.orelse, set(held))
                yield from self._walk_stmts(entry, info, stmt.finalbody, set(held))
            else:
                yield (stmt, frozenset(held))
                for sub in ast.walk(stmt):
                    if sub is not stmt and not isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
                    ):
                        yield (sub, frozenset(held))
            if ak:
                held.add(ak)
            rk = self._acq_rel_key(entry, info, stmt, "release")
            if rk:
                held.discard(rk)

    def _walk_expr(self, expr: ast.AST, held: Set[str]
                   ) -> Iterator[Tuple[ast.AST, FrozenSet[str]]]:
        fs = frozenset(held)
        for sub in ast.walk(expr):
            if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
                yield (sub, fs)

    # -------------------------------------------------------------- access collection
    def root_labels(self, idxs: Set[int]) -> str:
        return " + ".join(sorted(self.roots[i].label for i in idxs))

    def collect_accesses(self) -> List[_Access]:
        out: List[_Access] = []
        for entry in self.pm.entries:
            globals_ = self._module_globals.get(entry.path, set())
            for info in entry.model.functions:
                roots = self.roots_of.get(id(info))
                if not roots:
                    continue
                ctor = info.cls is not None and info.name in ("__init__", "__new__", "__post_init__")
                base = self.entry_lockset.get(id(info), frozenset())
                func_globals = {
                    n for node in _scoped_walk(info.node) if isinstance(node, ast.Global)
                    for n in node.names
                }
                test_spans = self._test_spans(info)
                for node, lockset in self._walk_locked(entry, info, base):
                    acc = self._classify(entry, info, node, lockset, globals_, func_globals, ctor)
                    if acc is None:
                        continue
                    acc.in_test = any(lo <= getattr(node, "lineno", 0) <= hi and c0 <= getattr(node, "col_offset", -1)
                                      for lo, hi, c0 in test_spans) if acc.kind == "read" else False
                    out.append(acc)
        return out

    @staticmethod
    def _test_spans(info: _FuncInfo) -> List[Tuple[int, int, int]]:
        spans = []
        for node in _scoped_walk(info.node):
            if isinstance(node, (ast.If, ast.While)):
                t = node.test
                spans.append((t.lineno, getattr(t, "end_lineno", t.lineno), 0))
        return spans

    def _sanction(self, entry, node: ast.AST) -> Optional[str]:
        line = getattr(node, "lineno", 0)
        src = entry.lines[line - 1] if 0 < line <= len(entry.lines) else ""
        m = _SINGLE_MUTATOR_RE.search(src)
        return "single-mutator" if m else None

    def _field_of(self, entry, info: _FuncInfo, expr: ast.AST,
                  globals_: Set[str], func_globals: Set[str]) -> Optional[Tuple[str, str, str]]:
        """Owning field of an attribute/name expression, or None when unattributable."""
        d = _dotted(expr)
        if d is None:
            return None
        if d[0] == "self" and len(d) >= 2 and info.cls is not None:
            if (entry.path, info.cls) not in self._shared_classes:
                return None  # instances never escape one thread (no shared anchor)
            return (entry.path, info.cls, d[1])
        if len(d) == 1:
            name = d[0]
            if name in func_globals or (name in globals_ and info.cls is None and info.parent is None):
                if name in self._module_locks.get(entry.path, ()):
                    return None
                return (entry.path, "<module>", name)
        return None

    def _classify(self, entry, info, node, lockset, globals_, func_globals, ctor) -> Optional[_Access]:
        # -- writes -------------------------------------------------------------
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                field = self._field_of(entry, info, base, globals_, func_globals)
                if field is None:
                    continue
                if ctor and field[1] == info.cls:
                    return None  # __init__-time store: the object has not escaped yet
                if field[2] in self._class_locks.get((entry.path, info.cls or ""), ()):
                    return None
                return _Access(field, "write", entry.path, node, lockset, info,
                               sanction=self._sanction(entry, node))
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            mname = node.func.attr
            if mname in _MUTATORS:
                field = self._field_of(entry, info, node.func.value, globals_, func_globals)
                if field is not None and not (ctor and field[1] == info.cls):
                    return _Access(field, "write", entry.path, node, lockset, info,
                                   atomic=mname in _ATOMIC_MUTATORS,
                                   sanction=self._sanction(entry, node))
            elif mname in _ITER_READS:
                field = self._field_of(entry, info, node.func.value, globals_, func_globals)
                if field is not None:
                    return _Access(field, "read", entry.path, node, lockset, info,
                                   sanction=self._sanction(entry, node))
        # -- reads (attribute loads only; filtered down to tests/iterations later) --
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            field = self._field_of(entry, info, node, globals_, func_globals)
            if field is not None:
                return _Access(field, "read", entry.path, node, lockset, info,
                               sanction=self._sanction(entry, node))
        if isinstance(node, (ast.For, ast.AsyncFor)):
            field = self._field_of(entry, info, node.iter, globals_, func_globals)
            if field is not None:
                acc = _Access(field, "read", entry.path, node.iter, lockset, info,
                              sanction=self._sanction(entry, node))
                acc.in_test = True  # iterating the raw field is a multi-step read
                return acc
        return None


# ===================================================================== rule drivers
def _lines_of(pm, path: str) -> Sequence[str]:
    for e in pm.entries:
        if e.path == path:
            return e.lines
    return []


def _lock_names(lockset: FrozenSet[str]) -> str:
    if not lockset:
        return "no lock"
    return " + ".join(sorted(k.rsplit("::", 1)[-1] for k in lockset))


def _rule_tpu021(cm: ConcurrencyModel) -> List[Finding]:
    by_field: Dict[Tuple[str, str, str], List[_Access]] = {}
    for acc in cm._accesses:
        if acc.kind == "write":
            by_field.setdefault(acc.field, []).append(acc)
    out: List[Finding] = []
    for field, writes in sorted(by_field.items()):
        if any(w.sanction for w in writes):
            continue  # a declared single-mutator field is sanctioned at every site
        best: Optional[Tuple[_Access, _Access]] = None
        for i, a in enumerate(writes):
            if a.atomic:
                continue
            ra = cm.roots_of.get(id(a.func), set())
            for b in writes[i:]:
                rb = cm.roots_of.get(id(b.func), set())
                if a.lockset & b.lockset:
                    continue
                pair_ok = any(
                    cm.roots[x].concurrent_with(cm.roots[y])
                    for x in ra for y in rb
                )
                if not pair_ok:
                    continue
                if b.atomic and b is not a:
                    continue
                cand = (a, b) if (len(a.lockset), a.node.lineno) <= (len(b.lockset), b.node.lineno) else (b, a)
                if best is None or (cand[0].path, cand[0].node.lineno) < (best[0].path, best[0].node.lineno):
                    best = cand
        if best is None:
            continue
        a, b = best
        ra = cm.roots_of.get(id(a.func), set())
        rb = cm.roots_of.get(id(b.func), set())
        other = "" if a.node is b.node else (
            f"; also written at {b.path}:{b.node.lineno} under {_lock_names(b.lockset)}"
            f" from {cm.root_labels(rb)}"
        )
        out.append(_finding(
            "TPU021", a.path, a.node, _lines_of(cm.pm, a.path),
            f"shared field {field[1]}.{field[2]!s} written under {_lock_names(a.lockset)}"
            f" from {cm.root_labels(ra)}{other} — concurrent writers with disjoint"
            " locksets lose updates. Guard both sites with one lock, or declare the"
            " protocol with '# jaxlint: single-mutator (racerun: <scenario>)' backed by"
            " a passing schedule (make jaxlint-race)",
        ))
    return out


def _rule_tpu023(cm: ConcurrencyModel) -> List[Finding]:
    writes: Dict[Tuple[str, str, str], List[_Access]] = {}
    for acc in cm._accesses:
        if acc.kind == "write":
            writes.setdefault(acc.field, []).append(acc)
    out: List[Finding] = []
    seen: Set[Tuple[str, int, Tuple[str, str, str]]] = set()
    for acc in cm._accesses:
        if acc.kind != "read" or not acc.in_test or acc.sanction:
            continue
        ws = writes.get(acc.field)
        if not ws or any(w.sanction for w in ws):
            continue
        guard = None
        for w in ws:
            guard = w.lockset if guard is None else (guard & w.lockset)
        if not guard or acc.lockset & guard:
            continue  # writes unguarded (TPU021's domain) or the read holds the guard
        ra = cm.roots_of.get(id(acc.func), set())
        conc = [
            w for w in ws
            if any(cm.roots[x].concurrent_with(cm.roots[y])
                   for x in ra for y in cm.roots_of.get(id(w.func), set()))
        ]
        if not conc:
            continue
        key = (acc.path, acc.node.lineno, acc.field)
        if key in seen:
            continue
        seen.add(key)
        w = conc[0]
        shape = "check-then-act on" if isinstance(acc.node, ast.Attribute) else "multi-step read of"
        out.append(_finding(
            "TPU023", acc.path, acc.node, _lines_of(cm.pm, acc.path),
            f"{shape} shared field {acc.field[1]}.{acc.field[2]} outside its guarding"
            f" lock ({_lock_names(guard)}) — a concurrent writer"
            f" ({cm.root_labels(cm.roots_of.get(id(w.func), set()))},"
            f" {w.path}:{w.node.lineno}) can move the field between the read and the"
            " action taken on it. Take the guard for the whole check-then-act region",
        ))
    return out


def _rule_tpu022(cm: ConcurrencyModel) -> List[Finding]:
    out: List[Finding] = []
    for entry in cm.pm.entries:
        serve_classes: Set[str] = set()
        for info in entry.model.functions:
            if info.cls is None:
                continue
            for node in _scoped_walk(info.node):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        d = _dotted(t)
                        if d and d[:2] == ["self", "_serve"] and len(d) == 2:
                            serve_classes.add(info.cls)
        for cls in sorted(serve_classes):
            methods = {fi.name: fi for fi in entry.model.functions if fi.cls == cls and fi.parent is None}
            ctor_reach = _class_closure(methods, {"__init__", "__new__"})
            for name, info in sorted(methods.items()):
                if name.startswith("_") or name in ctor_reach:
                    continue
                if not _touches_tensor_state(info):
                    continue
                if _quiesces(info, methods, set()):
                    continue
                out.append(_finding(
                    "TPU022", entry.path, info.node, entry.lines,
                    f"public host-access entry point {cls}.{name} touches tensor state"
                    " without routing through the quiesce seam — with an IngestEngine"
                    " attached (update_async/serve()), this observes a mid-window"
                    " state the drain is still mutating. Quiesce first"
                    " (docs/serving.md 'Host access & the quiesce contract')",
                ))
    return out


def _class_closure(methods: Dict[str, _FuncInfo], seeds: Set[str]) -> Set[str]:
    """Names of methods reachable from ``seeds`` via ``self.m()`` calls."""
    reach = set(s for s in seeds if s in methods)
    work = list(reach)
    while work:
        info = methods.get(work.pop())
        if info is None:
            continue
        for node in _scoped_walk(info.node):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and len(d) == 2 and d[0] == "self" and d[1] in methods and d[1] not in reach:
                    reach.add(d[1])
                    work.append(d[1])
    return reach


def _touches_tensor_state(info: _FuncInfo) -> bool:
    for node in _scoped_walk(info.node):
        d = _dotted(node) if isinstance(node, ast.Attribute) else None
        if d and len(d) >= 3 and d[0] == "self" and d[1] == "_state" and d[2] in _STATE_TENSOR_ATTRS:
            return True
    return False


def _quiesces(info: _FuncInfo, methods: Dict[str, _FuncInfo], seen: Set[str]) -> bool:
    if info.name in seen:
        return False
    seen.add(info.name)
    for node in _scoped_walk(info.node):
        if isinstance(node, ast.Call):
            if _final_name(node.func) == "quiesce":
                return True
            d = _dotted(node.func)
            if d and len(d) == 2 and d[0] == "self" and d[1] in methods:
                if _quiesces(methods[d[1]], methods, seen):
                    return True
    return False


def run_concurrency_rules(pm) -> List[Finding]:
    """Run TPU021/TPU022/TPU023 over a built ProjectModel (whole-program pass only).

    Computed fresh on every tree-cache miss — the per-module incremental cache never
    stores these findings (they depend on every module at once), and the tree-level
    cache key plus ``analyzer_fingerprint()`` (which hashes this file) keep cached
    results sound.
    """
    cm = ConcurrencyModel(pm)
    cm._accesses = cm.collect_accesses()
    findings = _rule_tpu021(cm) + _rule_tpu022(cm) + _rule_tpu023(cm)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def suppression_scenarios(pm) -> List[Dict[str, str]]:
    """Every ``single-mutator`` / ``disable=TPU021`` marker with its racerun scenario.

    The suppression contract (docs/static-analysis.md): a concurrency sanction must
    name the deterministic schedule that justifies it —
    ``# jaxlint: single-mutator (racerun: engine_enqueue_vs_quiesce)``. The test suite
    asserts every named scenario exists in :mod:`torchmetrics_tpu._lint.racerun` and
    passes.
    """
    import io
    import tokenize

    rows: List[Dict[str, str]] = []
    for entry in pm.entries:
        # tokenize so only REAL comments count — this module's own docstring spells
        # out the marker syntax and must not read as a shipped suppression
        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(entry.source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            continue
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            src, lineno = tok.string, tok.start[0]
            m = _SINGLE_MUTATOR_RE.search(src)
            if m:
                rows.append({
                    "path": entry.path, "line": str(lineno), "kind": "single-mutator",
                    "scenario": m.group("scenario") or "",
                })
            if re.search(r"#\s*jaxlint:\s*disable=[A-Z0-9, ]*TPU021", src):
                sm = re.search(r"racerun:\s*([\w.-]+)", src)
                rows.append({
                    "path": entry.path, "line": str(lineno), "kind": "disable",
                    "scenario": sm.group(1) if sm else "",
                })
    return rows
