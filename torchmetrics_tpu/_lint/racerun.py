"""racerun: deterministic-schedule race sanitizer for the tmrace concurrency rules.

The static half (:mod:`torchmetrics_tpu._lint.concurrency`) proves where concurrent
roots *can* collide; this module proves what actually happens there. It installs
preemption points — via ``threading.settrace`` line tracing — at the shared-access
sites the static pass identified, then drives small harness programs through SEEDED
interleaving permutations: one thread runs at a time, every park/grant decision comes
from a ``random.Random(seed)``, and the same seed replays the same schedule. That
closes the TPU021 contract loop:

- a *finding* is reproduced into a failing schedule (the synthetic lost-update fixture
  below fails deterministically at line granularity — the read and the write of the
  unlocked counter sit on separate lines, so a forced switch between them loses an
  update), and
- a *suppression* (``# jaxlint: single-mutator (racerun: <scenario>)``) carries a named
  scenario in :data:`SCENARIOS` that survives every explored interleaving of the REAL
  shipped code — engine enqueue-vs-quiesce, federation poll-vs-shutdown, flight-ring
  append-vs-snapshot, health-ledger evict-vs-probe (``make jaxlint-race``).

How the scheduler stays deterministic: every harness body parks at a start barrier
before its first statement, so the initial parked set is fixed; after that exactly one
thread holds a grant, runs to its next watched line, and parks again — the rng only
ever chooses among a deterministic set. Two caveats, both deliberate: (1) a granted
thread that blocks on a REAL lock held by a parked thread is detected by timeout and
the scheduler moves on (the blocked thread finishes its region once the holder is
granted — so lock-correct code may briefly overlap, which is exactly the situation
locks make safe); (2) threads the harness code spawns itself (the engine's drain) join
the schedule at their first watched line, so their arrival slot can vary — scenarios
over such code assert INVARIANTS over every schedule rather than trace equality, while
the fixed-body synthetic fixture is bit-deterministic and the unit tests pin that.

Python ≥3.12 would allow per-opcode tracing (``frame.f_trace_opcodes``) to split even
one-line ``x += 1`` races; line granularity plus the two-line fixture idiom covers the
same ground on every interpreter this repo supports.

Nothing here imports jax at module scope — scenarios lazy-import the subsystems they
drive, so ``python -m torchmetrics_tpu._lint.racerun --list`` works on a lint-only box.
"""
from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

#: cumulative per-process sanitizer counters, exported by ``obs.bench_extras()``
LAST_RACE_STATS: Dict[str, int] = {"race_schedules_run": 0, "race_findings": 0}

#: how long a granted thread may fail to re-park before the scheduler assumes it is
#: blocked on a real primitive and moves on (wall-clock; only blocking pays it)
_BLOCKED_TIMEOUT_S = 0.12
#: hard cap on grants per schedule — a runaway harness ends, it does not hang CI
_DEFAULT_SWITCH_BUDGET = 800


class Watch:
    """One preemption-point spec: a file suffix, optionally narrowed to funcs/lines."""

    __slots__ = ("file_suffix", "funcs", "lines")

    def __init__(self, file_suffix: str, funcs: Optional[FrozenSet[str]] = None,
                 lines: Optional[FrozenSet[int]] = None) -> None:
        self.file_suffix = file_suffix
        self.funcs = funcs
        self.lines = lines

    def matches_file(self, filename: str) -> bool:
        return filename.endswith(self.file_suffix)

    def matches(self, filename: str, func: str, lineno: int) -> bool:
        if not filename.endswith(self.file_suffix):
            return False
        if self.funcs is not None and func not in self.funcs:
            return False
        if self.lines is not None and lineno not in self.lines:
            return False
        return True


class ScheduleResult:
    """Outcome of one explored schedule."""

    __slots__ = ("seed", "trace", "error", "switches")

    def __init__(self, seed: int, trace: List[str], error: Optional[str], switches: int) -> None:
        self.seed = seed
        self.trace = trace
        self.error = error
        self.switches = switches

    @property
    def failed(self) -> bool:
        return self.error is not None


class _Gate:
    __slots__ = ("event",)

    def __init__(self) -> None:
        self.event = threading.Event()


class ScheduleRunner:
    """Run one seeded interleaving of ``bodies`` with parks at watched lines."""

    def __init__(self, watch: Sequence[Watch], seed: int,
                 switch_budget: int = _DEFAULT_SWITCH_BUDGET) -> None:
        self.watch = list(watch)
        self.rng = random.Random(seed)
        self.switch_budget = switch_budget
        self.trace: List[str] = []
        self.switches = 0
        self._arrival = threading.Condition()
        self._gates: Dict[str, _Gate] = {}
        self._parked: Dict[str, str] = {}  # name -> "file:line" it parked at
        self._finished: set = set()
        self._body_names: List[str] = []
        self._errors: List[str] = []
        self._free_run = False
        self._scheduler_ident = threading.get_ident()

    # ------------------------------------------------------------- trace machinery
    def _tracefunc(self, frame, event, arg):
        if event != "call":
            return None
        fn = frame.f_code.co_filename
        for w in self.watch:
            if w.matches_file(fn):
                return self._linetrace
        return None

    def _linetrace(self, frame, event, arg):
        if event == "line" and not self._free_run:
            code = frame.f_code
            for w in self.watch:
                if w.matches(code.co_filename, code.co_name, frame.f_lineno):
                    self._park(f"{code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno}")
                    break
        return self._linetrace

    def _thread_name(self) -> str:
        return threading.current_thread().name

    def _park(self, where: str) -> None:
        if threading.get_ident() == self._scheduler_ident or self._free_run:
            return
        name = self._thread_name()
        gate = self._gates.get(name)
        if gate is None:
            with self._arrival:
                gate = self._gates.setdefault(name, _Gate())
        with self._arrival:
            self._parked[name] = where
            self._arrival.notify_all()
        gate.event.wait()
        gate.event.clear()

    def _wrap(self, name: str, fn: Callable[[], None]) -> Callable[[], None]:
        def body() -> None:
            try:
                self._park("<start>")  # start barrier: deterministic initial set
                fn()
            except Exception as err:  # noqa: BLE001 - surfaced as a schedule failure
                self._errors.append(f"{name}: {err!r}")
            finally:
                with self._arrival:
                    self._finished.add(name)
                    self._parked.pop(name, None)
                    self._arrival.notify_all()
        return body

    # ----------------------------------------------------------------- scheduling
    def run(self, bodies: Sequence[Tuple[str, Callable[[], None]]],
            join_timeout: float = 20.0) -> None:
        self._body_names = [name for name, _ in bodies]
        threads = [
            threading.Thread(target=self._wrap(name, fn), name=name, daemon=True)
            for name, fn in bodies
        ]
        old_trace = threading._trace_hook  # noqa: SLF001 - save to restore exactly
        threading.settrace(self._tracefunc)
        try:
            for t in threads:
                t.start()
            self._schedule_loop()
        finally:
            threading.settrace(old_trace)
            with self._arrival:
                self._free_run = True  # stragglers (spawned threads) run free now
                for gate in self._gates.values():
                    gate.event.set()
            for t in threads:
                t.join(timeout=join_timeout)
                if t.is_alive():
                    self._errors.append(f"{t.name}: did not finish (possible deadlock)")

    def _live_bodies(self) -> List[str]:
        return [n for n in self._body_names if n not in self._finished]

    def _schedule_loop(self) -> None:
        granted: Optional[str] = None
        while True:
            with self._arrival:
                # wait until the granted thread re-parks/finishes, or — before any
                # grant — until every body has reached the start barrier
                deadline = time.monotonic() + _BLOCKED_TIMEOUT_S
                while True:
                    live = self._live_bodies()
                    if not live:
                        return
                    if granted is None:
                        waiting_for = [n for n in live if n not in self._parked]
                    else:
                        waiting_for = [granted] if (
                            granted not in self._parked and granted not in self._finished
                        ) else []
                    if not waiting_for:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break  # blocked on a real primitive: move on
                    self._arrival.wait(remaining)
                live = self._live_bodies()
                if not live:
                    return
                choices = sorted(self._parked)
                if not choices:
                    continue  # everyone is running free or blocked; wait again
                pick = choices[0] if len(choices) == 1 else self.rng.choice(choices)
                where = self._parked.pop(pick)
                self.trace.append(f"{pick}@{where}")
                granted = pick
                gate = self._gates[pick]
            gate.event.set()
            self.switches += 1
            if self.switches >= self.switch_budget:
                return


def run_schedule(
    build: Callable[[], Tuple[Sequence[Tuple[str, Callable[[], None]]], Callable[[], None]]],
    watch: Sequence[Watch],
    seed: int,
    switch_budget: int = _DEFAULT_SWITCH_BUDGET,
) -> ScheduleResult:
    """Run ONE seeded interleaving: fresh state from ``build()``, then the check."""
    bodies, check = build()
    runner = ScheduleRunner(watch, seed=seed, switch_budget=switch_budget)
    runner.run(bodies)
    error: Optional[str] = "; ".join(runner._errors) or None
    if error is None:
        try:
            check()
        except Exception as err:  # noqa: BLE001 - invariant violation == race found
            error = f"check: {err!r}"
    return ScheduleResult(seed=seed, trace=runner.trace, error=error, switches=runner.switches)


def explore(
    build: Callable[[], Tuple[Sequence[Tuple[str, Callable[[], None]]], Callable[[], None]]],
    watch: Sequence[Watch],
    seed: int = 0,
    schedules: int = 10,
    switch_budget: int = _DEFAULT_SWITCH_BUDGET,
) -> Dict[str, Any]:
    """Explore ``schedules`` seeded interleavings; returns a summary dict.

    Schedule k runs with seed ``seed * 10_000 + k`` — derived, not sequential, so two
    scenarios sharing a base seed still explore different permutations. The result's
    ``failures`` lists ``(schedule_seed, error, trace)`` for every failing schedule;
    determinism means re-running with the same base seed reproduces the same list.
    """
    failures: List[Dict[str, Any]] = []
    run = 0
    for k in range(schedules):
        res = run_schedule(build, watch, seed=seed * 10_000 + k, switch_budget=switch_budget)
        run += 1
        if res.failed:
            failures.append({
                "seed": res.seed,
                "error": res.error,
                "trace": res.trace[-24:],  # the decisive suffix; full trace is huge
            })
    LAST_RACE_STATS["race_schedules_run"] += run
    LAST_RACE_STATS["race_findings"] += len(failures)
    return {"schedules_run": run, "failures": failures, "passed": not failures}


# ------------------------------------------------------------------ synthetic fixture
class RacyCounter:
    """The canonical TPU021 lost update, with the read/write split across lines so the
    line-granularity scheduler can preempt between them (see the module docstring)."""

    def __init__(self) -> None:
        self.value = 0

    def inc(self) -> None:
        v = self.value
        self.value = v + 1


class LockedCounter:
    """The fixed counterpart: the same read-modify-write under a lock."""

    def __init__(self) -> None:
        self.value = 0
        self._lock = threading.Lock()

    def inc(self) -> None:
        with self._lock:
            v = self.value
            self.value = v + 1


def lost_update_fixture(locked: bool, increments: int = 3,
                        threads: int = 2) -> Callable[[], Tuple[list, Callable[[], None]]]:
    """Harness builder for the synthetic fixture (used by tests and ``--scenario demo``)."""
    def build():
        counter = LockedCounter() if locked else RacyCounter()

        def worker():
            for _ in range(increments):
                counter.inc()

        def check():
            expect = increments * threads
            assert counter.value == expect, (
                f"lost update: counted {counter.value}, expected {expect}"
            )
        return [(f"T{i}", worker) for i in range(threads)], check
    return build


_FIXTURE_WATCH = (Watch("_lint/racerun.py", funcs=frozenset({"inc"})),)


# ------------------------------------------------------- static-pass preemption sites
_shared_lines_cache: Optional[Dict[str, FrozenSet[int]]] = None


def shared_access_lines() -> Dict[str, FrozenSet[int]]:
    """Preemption sites from the static pass: display path -> shared-access linenos.

    This is the tmrace tie-in the scenarios run on: the scheduler only parks where the
    concurrency analysis says a shared field is touched, which keeps a schedule to a
    handful of decisive switch points instead of every line of the engine. Computed
    once per process (one ProjectModel build over the installed tree).
    """
    global _shared_lines_cache
    if _shared_lines_cache is not None:
        return _shared_lines_cache
    from pathlib import Path

    import torchmetrics_tpu
    from torchmetrics_tpu._lint.concurrency import ConcurrencyModel
    from torchmetrics_tpu._lint.core import iter_python_files
    from torchmetrics_tpu._lint.project import ProjectModel

    root = Path(torchmetrics_tpu.__file__).resolve().parent
    sources = []
    for fp, display in iter_python_files([root]):
        try:
            sources.append((display, fp.read_text(encoding="utf-8")))
        except (OSError, UnicodeDecodeError):
            continue
    pm = ProjectModel(sources)
    cm = ConcurrencyModel(pm)
    lines: Dict[str, set] = {}
    for acc in cm.collect_accesses():
        lines.setdefault(acc.path, set()).add(acc.node.lineno)
    _shared_lines_cache = {p: frozenset(ls) for p, ls in lines.items()}
    return _shared_lines_cache


def _watch_for(path_suffix: str, funcs: Optional[FrozenSet[str]] = None) -> Watch:
    """Watch a shipped file at its static-pass shared-access lines (fall back to all
    lines of ``funcs`` when the analysis finds none — e.g. a freshly-sanctioned file)."""
    for display, lines in shared_access_lines().items():
        if display.endswith(path_suffix):
            return Watch(path_suffix, funcs=None, lines=lines)
    return Watch(path_suffix, funcs=funcs)


# ------------------------------------------------------------------ shipped scenarios
def scenario_engine_enqueue_vs_quiesce(seed: int = 0, schedules: int = 3) -> Dict[str, Any]:
    """Producer enqueues against the real drain while a second thread quiesces.

    Backs the ``single-mutator`` sanction on ``IngestEngine._fence``: the drain is the
    sole fence writer while the window is non-empty, and quiesce only clears it after
    proving the window empty under ``_cond`` — so every interleaving must end with
    zero fence breaks and exact stats accounting.
    """
    from torchmetrics_tpu.serve.engine import IngestEngine
    from torchmetrics_tpu.serve.options import ServeOptions

    class _Store:
        def __init__(self) -> None:
            self.generation = 0

    class _Target:
        def __init__(self) -> None:
            self._state = _Store()
            self.applied = 0

        def update(self, x):
            self.applied += 1
            self._state.generation += 1

    def build():
        target = _Target()
        eng = IngestEngine(target, ServeOptions(max_inflight=8, coalesce=1,
                                                queue_timeout_s=10.0))

        def producer():
            for i in range(3):
                eng.enqueue((i,), {})

        def quiescer():
            eng.quiesce(timeout=10.0)

        def check():
            try:
                eng.quiesce(timeout=10.0)
                st = eng.stats()
                assert st["fence_breaks"] == 0, f"fence broke: {st}"
                assert st["committed"] == st["enqueued"] == 3, f"lost batches: {st}"
                assert target.applied == 3, f"applied {target.applied} != 3"
            finally:
                eng.close()
        return [("producer", producer), ("quiescer", quiescer)], check

    watch = [_watch_for("serve/engine.py",
                        funcs=frozenset({"enqueue", "_admit", "quiesce", "_apply_window"}))]
    return explore(build, watch, seed=seed, schedules=schedules)


def scenario_flight_ring_append_vs_snapshot(seed: int = 0, schedules: int = 6) -> Dict[str, Any]:
    """Two recorders race a snapshotter on one FlightRecorder ring.

    The PR 15 "snapshot orders by seq" claim, scheduled: under every interleaving the
    raw ring order must equal sequence order, ``last_seq`` must never regress, and
    every mid-race snapshot must be internally monotonic (the TPU021 fix locks the seq
    draw + cursor + append into one region).
    """
    from torchmetrics_tpu.obs.flightrec import FlightRecorder

    def build():
        rec = FlightRecorder(maxlen=32)
        snaps: List[Dict[str, Any]] = []

        def writer_a():
            for i in range(4):
                rec.record("race.a", i=i)

        def writer_b():
            for i in range(4):
                rec.record("race.b", i=i)

        def reader():
            snaps.append(rec.snapshot())
            snaps.append(rec.snapshot())

        def check():
            ring = [e["seq"] for e in rec.events()]
            assert ring == sorted(ring), f"ring order != seq order: {ring}"
            assert rec.last_seq == ring[-1], (rec.last_seq, ring[-1])
            final = rec.snapshot()
            assert final["recorded"] == 8 and final["dropped"] == 0, final
            for s in snaps:
                seqs = [e["seq"] for e in s["events"]]
                assert seqs == sorted(seqs), f"snapshot not monotonic: {seqs}"
                assert not seqs or s["last_seq"] >= seqs[-1], s["last_seq"]
        return [("writer-a", writer_a), ("writer-b", writer_b), ("reader", reader)], check

    watch = [_watch_for("obs/flightrec.py", funcs=frozenset({"record", "snapshot"}))]
    return explore(build, watch, seed=seed, schedules=schedules)


def scenario_federation_poll_vs_shutdown(seed: int = 0, schedules: int = 4) -> Dict[str, Any]:
    """Concurrent pollers race a payload reader and the close path's check-then-act.

    Drives the last-good-parse stale cache: every peer fetch fails, so each poll
    rewrites ``_state`` entries preserving the stale parse under ``_lock``, while a
    reader pulls ``payload()``/``render()`` and a closer flips a ``_closed``-style
    flag — the shapes TPU021/TPU023 police in federation code.
    """
    from torchmetrics_tpu.obs.federation import Federator, Peer

    def build():
        calls = {"n": 0}

        def flaky_fetch(url: str) -> bytes:
            calls["n"] += 1
            if calls["n"] % 3 == 0:
                raise OSError("peer unreachable (scheduled)")
            return b"# TYPE tm_x counter\ntm_x_total{rank=\"0\"} 1.0\n# EOF\n"

        fed = Federator([Peer("p0", "http://peer-0:9090"),
                         Peer("p1", "http://peer-1:9090")], fetch_fn=flaky_fetch)
        closed = {"flag": False}

        def poller():
            for _ in range(2):
                if not closed["flag"]:
                    fed.poll()

        def reader():
            fed.payload()
            fed.render()

        def closer():
            closed["flag"] = True

        def check():
            summary = fed.poll()
            assert summary["peers"] == 2, summary
            payload = fed.payload()
            assert payload["tier"] == "fleet", payload.get("tier")
            states = fed.peer_states()
            assert set(states) <= {"p0", "p1"}, set(states)
            # the stale-beats-blind contract mid-race: a down peer that ever parsed
            # cleanly must still carry that parse
            for st in states.values():
                if not st["up"] and st["error"] is None:
                    raise AssertionError(f"down peer lost its error record: {st}")
        return [("poller-a", poller), ("poller-b", poller), ("reader", reader),
                ("closer", closer)], check

    watch = [_watch_for("obs/federation.py",
                        funcs=frozenset({"poll", "payload", "render", "active_incidents"}))]
    return explore(build, watch, seed=seed, schedules=schedules)


def scenario_health_ledger_evict_vs_probe(seed: int = 0, schedules: int = 5) -> Dict[str, Any]:
    """Failure recorder races the gather-group prober over a fixed rank set.

    The ledger is main-thread-only in the shipped tree (the static pass confirms no
    concurrent writer), but ROADMAP item 5's per-tier ledgers will change that — this
    schedule pins the contract they must keep: a fixed rank population never loses a
    failure record, and eviction/probe partitions stay consistent mid-race.
    """
    from torchmetrics_tpu.parallel.sync import HealthLedger

    def build():
        led = HealthLedger(evict_after=2, probe_backoff_s=0.0)
        for r in range(4):
            led.record_success(r)

        def failer():
            led.record_failure(2)
            led.record_failure(2)
            led.record_failure(3)

        def prober():
            for _ in range(3):
                led.gather_group(4)
                led.evicted_ranks()

        def check():
            assert 2 in led.evicted_ranks(), led.report()
            group, probes = led.gather_group(4)
            assert set(group) | set(probes) == {0, 1, 2, 3}, (group, probes)
            rep = led.report()
            assert rep[2]["consecutive_failures"] == 2, rep[2]
            assert rep[3]["total_failures"] == 1, rep[3]
        return [("failer", failer), ("prober", prober)], check

    watch = [_watch_for("parallel/sync.py",
                        funcs=frozenset({"record_failure", "record_success",
                                         "gather_group", "evicted_ranks"}))]
    return explore(build, watch, seed=seed, schedules=schedules)


#: every named scenario a concurrency suppression may cite (the contract checker in
#: tests/unittests/lint asserts each shipped marker names a key of this dict)
SCENARIOS: Dict[str, Callable[..., Dict[str, Any]]] = {
    "engine_enqueue_vs_quiesce": scenario_engine_enqueue_vs_quiesce,
    "flight_ring_append_vs_snapshot": scenario_flight_ring_append_vs_snapshot,
    "federation_poll_vs_shutdown": scenario_federation_poll_vs_shutdown,
    "health_ledger_evict_vs_probe": scenario_health_ledger_evict_vs_probe,
}


def run_all(seed: int = 0, schedules: Optional[int] = None) -> Dict[str, Any]:
    """Run every shipped scenario; the ``make jaxlint-race`` entry point."""
    results: Dict[str, Any] = {}
    ok = True
    for name, fn in SCENARIOS.items():
        res = fn(seed=seed, schedules=schedules) if schedules else fn(seed=seed)
        results[name] = res
        ok = ok and res["passed"]
    return {"passed": ok, "scenarios": results,
            "schedules_run": sum(r["schedules_run"] for r in results.values())}


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchmetrics_tpu._lint.racerun",
        description="Deterministic schedule explorer for the tmrace concurrency rules",
    )
    parser.add_argument("--scenario", help="run one scenario (or 'demo' for the synthetic"
                                           " lost-update fixture); default: all")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--schedules", type=int, default=None,
                        help="interleavings per scenario (default: per-scenario)")
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument("--list", action="store_true", help="list scenarios and exit")
    args = parser.parse_args(argv)

    if args.list:
        for name in SCENARIOS:
            print(name)
        return 0
    if args.scenario == "demo":
        racy = explore(lost_update_fixture(locked=False), _FIXTURE_WATCH,
                       seed=args.seed, schedules=args.schedules or 12)
        fixed = explore(lost_update_fixture(locked=True), _FIXTURE_WATCH,
                        seed=args.seed, schedules=args.schedules or 12)
        out = {"passed": bool(racy["failures"]) and fixed["passed"],
               "racy_failures": len(racy["failures"]), "fixed": fixed["passed"]}
    elif args.scenario:
        if args.scenario not in SCENARIOS:
            print(f"unknown scenario {args.scenario!r}; see --list", file=sys.stderr)
            return 2
        fn = SCENARIOS[args.scenario]
        out = fn(seed=args.seed, schedules=args.schedules) if args.schedules \
            else fn(seed=args.seed)
    else:
        out = run_all(seed=args.seed, schedules=args.schedules)

    if args.as_json:
        print(json.dumps(out, indent=2))
    else:
        if "scenarios" in out:
            for name, res in out["scenarios"].items():
                status = "ok" if res["passed"] else "RACE"
                print(f"{status:4s} {name}: {res['schedules_run']} schedule(s),"
                      f" {len(res['failures'])} failure(s)")
                for f in res["failures"]:
                    print(f"     seed={f['seed']}: {f['error']}")
                    print(f"     trace: {' -> '.join(f['trace'])}")
        print(f"racerun: {'all scenarios passed' if out['passed'] else 'RACE FOUND'}")
    return 0 if out["passed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
