"""Content-fingerprint incremental cache for jaxlint.

Whole-program analysis re-parses the entire tree on every run; this cache makes repeat
runs pay only for what changed:

- **tree fast path** — when no analyzed file changed (the common CI re-run), the final
  finding list is served from the cache without parsing a single file;
- **per-module reuse** — when some files changed, every module still has to be *parsed*
  (the project pass needs all symbol tables), but rule execution — the expensive part —
  is skipped for modules whose source digest AND interprocedural-marks fingerprint both
  match the cached entry. Marks are pure functions of the whole tree
  (``project.ProjectModel.marks_fingerprint``), so matching (digest, marks) guarantees
  identical rule output.

Every key folds in the **analyzer fingerprint** (a digest of the ``_lint`` package's own
sources) and the active ``--select`` set, so editing a rule or changing rule selection
invalidates everything automatically — there is no version constant to forget to bump.

The cache is a plain JSON file (default ``.jaxlint_cache.json`` in the working directory,
override via ``TM_TPU_LINT_CACHE`` or ``--cache``); a corrupt or stale file is treated as
empty, and save failures are swallowed — a cache must never take the lint run down.
"""
from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

ENV_CACHE_PATH = "TM_TPU_LINT_CACHE"
DEFAULT_CACHE_PATH = ".jaxlint_cache.json"

_ANALYZER_FP: Optional[str] = None


def analyzer_fingerprint() -> str:
    """Digest of the ``_lint`` package's own sources (cached per process).

    Part of every cache key: cached findings are only as current as the rules that
    produced them, so any analyzer edit invalidates the whole cache.
    """
    global _ANALYZER_FP
    if _ANALYZER_FP is None:
        h = hashlib.sha256()
        pkg = Path(__file__).resolve().parent
        for fp in sorted(pkg.glob("*.py")):
            h.update(fp.name.encode())
            h.update(fp.read_bytes())
        _ANALYZER_FP = h.hexdigest()[:16]
    return _ANALYZER_FP


def source_digest(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8", "surrogatepass")).hexdigest()[:16]


def tree_key(digests: Sequence[Tuple[str, str]], select_key: str) -> str:
    """One digest over the whole analyzed tree: (path, source digest) pairs + selection."""
    h = hashlib.sha256()
    h.update(analyzer_fingerprint().encode())
    h.update(select_key.encode())
    for path, digest in sorted(digests):
        h.update(path.encode())
        h.update(digest.encode())
    return h.hexdigest()[:16]


def marks_digest(fingerprint: str) -> str:
    return hashlib.sha256(fingerprint.encode()).hexdigest()[:16]


class LintCache:
    """Load/consult/update one cache file; ``hits``/``misses`` count per-module reuse."""

    def __init__(self, path: Any) -> None:
        self.path = Path(path)
        self.hits = 0
        self.misses = 0
        self._tree: Dict[str, Any] = {}
        self._modules: Dict[str, Dict[str, Any]] = {}
        try:
            payload = json.loads(self.path.read_text(encoding="utf-8"))
            if (
                isinstance(payload, dict)
                and payload.get("tool") == "jaxlint-cache"
                and payload.get("analyzer") == analyzer_fingerprint()
            ):
                self._tree = payload.get("tree", {}) or {}
                self._modules = payload.get("modules", {}) or {}
        except (OSError, ValueError):
            pass  # missing or corrupt cache == empty cache

    # ------------------------------------------------------------------------ tree level
    def tree_findings(self, key: str) -> Optional[List[Dict[str, Any]]]:
        if self._tree.get("key") == key:
            return list(self._tree.get("findings", []))
        return None

    def set_tree(self, key: str, findings: List[Dict[str, Any]]) -> None:
        self._tree = {"key": key, "findings": findings}

    # ---------------------------------------------------------------------- module level
    def module_findings(
        self, path: str, digest: str, marks: str, select_key: str
    ) -> Optional[List[Dict[str, Any]]]:
        entry = self._modules.get(path)
        if (
            entry is not None
            and entry.get("digest") == digest
            and entry.get("marks") == marks
            and entry.get("select", "") == select_key
        ):
            self.hits += 1
            return list(entry.get("findings", []))
        self.misses += 1
        return None

    def set_module(
        self, path: str, digest: str, marks: str, select_key: str,
        findings: List[Dict[str, Any]],
    ) -> None:
        self._modules[path] = {
            "digest": digest, "marks": marks, "select": select_key, "findings": findings,
        }

    # --------------------------------------------------------------------------- persist
    def save(self) -> None:
        payload = {
            "version": 1,
            "tool": "jaxlint-cache",
            "analyzer": analyzer_fingerprint(),
            "tree": self._tree,
            "modules": self._modules,
        }
        try:
            tmp = self.path.with_suffix(self.path.suffix + ".tmp")
            tmp.write_text(json.dumps(payload), encoding="utf-8")
            tmp.replace(self.path)
        except OSError:
            pass  # read-only checkout / sandbox: run uncached rather than fail
