"""jaxlint analysis driver: file walking, suppression comments, output formats.

The driver is deliberately stdlib-only (``ast`` + ``json``) so the analyzer imports in
milliseconds, runs in any environment the package installs into (no jax initialisation —
a lint pass must never touch an accelerator), and can execute inside CI sandboxes that
have no device at all. Rule logic lives in :mod:`torchmetrics_tpu._lint.rules`; baseline
bookkeeping in :mod:`torchmetrics_tpu._lint.baseline`.

Suppression: a finding is waived when its source line carries a marker comment —

    value = float(result)  # jaxlint: disable=TPU001
    value = float(result)  # jaxlint: disable=TPU001,TPU003
    value = float(result)  # jaxlint: disable

A bare ``disable`` (no ``=``) waives every rule on that line. Suppressions are counted in
the run summary so a sweep of blanket-disables stays visible.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

_SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*disable(?:=(?P<rules>[A-Z0-9, ]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer diagnostic, anchored to a source location.

    ``fingerprint`` (the normalised source line) — not the line number — is the baseline
    matching key, so unrelated edits that renumber a file do not invalidate the baseline.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str

    @property
    def fingerprint(self) -> str:
        return " ".join(self.snippet.split())

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.fingerprint)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


def _suppressed_rules(line: str) -> Optional[set]:
    """Rule ids waived on ``line``; empty set means 'all rules'; None means no marker."""
    m = _SUPPRESS_RE.search(line)
    if m is None:
        return None
    rules = m.group("rules")
    if rules is None:
        return set()
    return {r.strip() for r in rules.split(",") if r.strip()}


def analyze_source(
    source: str,
    path: str = "<string>",
    select: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run every (selected) rule over one Python source string.

    Returns findings sorted by location, with line-level suppression comments applied.

        >>> fs = analyze_source("def f(preds):\\n    return preds.item()\\n", path="snippet.py")
        >>> [f.rule for f in fs]
        ['TPU001']
        >>> analyze_source("def f(preds):\\n    return preds.item()  # jaxlint: disable=TPU001\\n")
        []
    """
    from torchmetrics_tpu._lint.rules import run_rules

    try:
        tree = ast.parse(source)
    except SyntaxError as err:
        line = err.lineno or 1
        return [
            Finding(
                rule="TPU000",
                path=path,
                line=line,
                col=(err.offset or 1) - 1,
                message=f"file does not parse: {err.msg}",
                snippet=(source.splitlines()[line - 1] if source.splitlines() else "").strip(),
            )
        ]
    lines = source.splitlines()
    findings = []
    for f in run_rules(tree, lines, path):
        if select and f.rule not in select:
            continue
        src_line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        waived = _suppressed_rules(src_line)
        if waived is not None and (not waived or f.rule in waived):
            continue
        findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(roots: Sequence[Any]) -> Iterable[Tuple[Path, str]]:
    """Yield ``(file_path, display_path)`` for every ``.py`` under the given roots.

    Display paths are rooted at each root's basename (``torchmetrics_tpu/metric.py``)
    so results are identical whether the tree is scanned from a source checkout or from
    site-packages — which keeps one baseline valid for both.
    """
    for root in roots:
        root = Path(root)
        if root.is_file():
            yield root, root.name
            continue
        for fp in sorted(root.rglob("*.py")):
            yield fp, (Path(root.name) / fp.relative_to(root)).as_posix()


def analyze_paths(roots: Sequence[Any], select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Analyze every Python file under ``roots``; findings sorted by path/line."""
    findings: List[Finding] = []
    for fp, display in iter_python_files(roots):
        try:
            source = fp.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError):
            continue
        findings.extend(analyze_source(source, path=display, select=select))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ------------------------------------------------------------------------ output formats
def render_text(new: List[Finding], baselined: int, stale: List[Dict[str, Any]]) -> str:
    lines = [f.render() for f in new]
    per_rule: Dict[str, int] = {}
    for f in new:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    rule_part = ", ".join(f"{k}={v}" for k, v in sorted(per_rule.items())) or "none"
    lines.append(
        f"jaxlint: {len(new)} new finding(s) [{rule_part}], {baselined} baselined,"
        f" {len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    )
    for entry in stale:
        lines.append(
            f"  stale baseline entry: {entry['rule']} {entry['path']} :: {entry['fingerprint']!r}"
        )
    return "\n".join(lines)


def render_json(new: List[Finding], baselined: int, stale: List[Dict[str, Any]]) -> str:
    return json.dumps(
        {
            "tool": "jaxlint",
            "new": [f.to_dict() for f in new],
            "new_count": len(new),
            "baselined_count": baselined,
            "stale_baseline_entries": stale,
        },
        indent=2,
    )


def render_sarif(new: List[Finding], rule_index: Dict[str, str]) -> str:
    """Minimal SARIF 2.1.0 document (one run, one result per new finding)."""
    rules = [
        {"id": rid, "shortDescription": {"text": desc}}
        for rid, desc in sorted(rule_index.items())
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": f.line, "startColumn": f.col + 1},
                    }
                }
            ],
        }
        for f in new
    ]
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {"driver": {"name": "jaxlint", "rules": rules}},
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)
