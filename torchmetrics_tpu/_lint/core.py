"""jaxlint analysis driver: file walking, suppression comments, output formats.

The driver is deliberately stdlib-only (``ast`` + ``json``) so the analyzer imports in
milliseconds, runs in any environment the package installs into (no jax initialisation —
a lint pass must never touch an accelerator), and can execute inside CI sandboxes that
have no device at all. Rule logic lives in :mod:`torchmetrics_tpu._lint.rules`; baseline
bookkeeping in :mod:`torchmetrics_tpu._lint.baseline`.

Suppression: a finding is waived when its source line carries a marker comment —

    value = float(result)  # jaxlint: disable=TPU001
    value = float(result)  # jaxlint: disable=TPU001,TPU003
    value = float(result)  # jaxlint: disable

A bare ``disable`` (no ``=``) waives every rule on that line. Suppressions are counted in
the run summary so a sweep of blanket-disables stays visible.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: run statistics of the most recent :func:`analyze_sources` call in this process —
#: surfaced by ``package_lint_status()`` and ``obs.bench_extras()`` (lint_runtime_ms,
#: lint_cache_hits) so the incremental-cache win shows up in bench rounds.
LAST_RUN_STATS: Dict[str, Any] = {}

_SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*disable(?:=(?P<rules>[A-Z0-9, ]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One analyzer diagnostic, anchored to a source location.

    ``fingerprint`` (the normalised source line) — not the line number — is the baseline
    matching key, so unrelated edits that renumber a file do not invalidate the baseline.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str

    @property
    def fingerprint(self) -> str:
        return " ".join(self.snippet.split())

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.fingerprint)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


def _suppressed_rules(line: str) -> Optional[set]:
    """Rule ids waived on ``line``; empty set means 'all rules'; None means no marker."""
    m = _SUPPRESS_RE.search(line)
    if m is None:
        return None
    rules = m.group("rules")
    if rules is None:
        return set()
    return {r.strip() for r in rules.split(",") if r.strip()}


def _syntax_error_finding(source: str, path: str, err: SyntaxError) -> Finding:
    line = err.lineno or 1
    return Finding(
        rule="TPU000",
        path=path,
        line=line,
        col=(err.offset or 1) - 1,
        message=f"file does not parse: {err.msg}",
        snippet=(source.splitlines()[line - 1] if source.splitlines() else "").strip(),
    )


def _filter_findings(
    findings: Iterable[Finding], lines: Sequence[str], select: Optional[Sequence[str]]
) -> List[Finding]:
    """Apply rule selection and line-level suppression comments; sort by location."""
    kept = []
    for f in findings:
        if select and f.rule not in select:
            continue
        src_line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        waived = _suppressed_rules(src_line)
        if waived is not None and (not waived or f.rule in waived):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def analyze_source(
    source: str,
    path: str = "<string>",
    select: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run every (selected) rule over one Python source string — per-module only.

    This is the module-local view: no interprocedural marks, no project context (use
    :func:`analyze_paths` for the whole-program pass). Returns findings sorted by
    location, with line-level suppression comments applied.

        >>> fs = analyze_source("def f(preds):\\n    return preds.item()\\n", path="snippet.py")
        >>> [f.rule for f in fs]
        ['TPU001']
        >>> analyze_source("def f(preds):\\n    return preds.item()  # jaxlint: disable=TPU001\\n")
        []
    """
    from torchmetrics_tpu._lint.rules import run_rules

    try:
        tree = ast.parse(source)
    except SyntaxError as err:
        return [_syntax_error_finding(source, path, err)]
    lines = source.splitlines()
    return _filter_findings(run_rules(tree, lines, path), lines, select)


def iter_python_files(roots: Sequence[Any]) -> Iterable[Tuple[Path, str]]:
    """Yield ``(file_path, display_path)`` for every ``.py`` under the given roots.

    Display paths are rooted at each root's basename (``torchmetrics_tpu/metric.py``)
    so results are identical whether the tree is scanned from a source checkout or from
    site-packages — which keeps one baseline valid for both.
    """
    for root in roots:
        root = Path(root)
        if root.is_file():
            yield root, root.name
            continue
        for fp in sorted(root.rglob("*.py")):
            yield fp, (Path(root.name) / fp.relative_to(root)).as_posix()


def analyze_paths(
    roots: Sequence[Any],
    select: Optional[Sequence[str]] = None,
    project: bool = True,
    cache: Optional[Any] = None,
) -> List[Finding]:
    """Analyze every Python file under ``roots``; findings sorted by path/line.

    ``project=True`` (the default) runs the whole-program pass: all files are modeled
    together, interprocedural marks (jit context, device params, hot paths, donating
    callables — see ``_lint/project.py``) propagate across module boundaries, and
    cross-module findings carry a ``via:`` call path. ``project=False`` is the legacy
    per-module mode (each file analyzed in isolation).

    ``cache`` is an optional :class:`torchmetrics_tpu._lint.cache.LintCache`: unchanged
    trees are served without parsing, and partially-changed trees skip rule execution for
    every module whose (source digest, marks fingerprint) pair still matches.
    """
    sources: List[Tuple[str, str]] = []
    for fp, display in iter_python_files(roots):
        try:
            sources.append((display, fp.read_text(encoding="utf-8")))
        except (OSError, UnicodeDecodeError):
            continue
    return analyze_sources(sources, select=select, project=project, cache=cache)


def analyze_sources(
    sources: Sequence[Tuple[str, str]],
    select: Optional[Sequence[str]] = None,
    project: bool = True,
    cache: Optional[Any] = None,
) -> List[Finding]:
    """Analyze ``(display_path, source)`` pairs (the driver behind :func:`analyze_paths`)."""
    import time

    t0 = time.perf_counter()
    select_key = ",".join(sorted(select)) if select else ""
    findings: List[Finding] = []
    tkey = None
    if cache is not None:
        from torchmetrics_tpu._lint.cache import source_digest, tree_key

        digests = {path: source_digest(src) for path, src in sources}
        tkey = tree_key(list(digests.items()), select_key)
        hit = cache.tree_findings(tkey)
        if hit is not None:
            cache.hits += len(sources)
            findings = [Finding(**d) for d in hit]
            LAST_RUN_STATS.update(
                runtime_ms=round((time.perf_counter() - t0) * 1e3, 2),
                cache_hits=cache.hits, cache_misses=cache.misses,
                files=len(sources), mode="tree-cache",
            )
            return findings

    if project:
        from torchmetrics_tpu._lint.cache import marks_digest
        from torchmetrics_tpu._lint.project import ProjectModel
        from torchmetrics_tpu._lint.rules import run_rules

        pm = ProjectModel(sources)
        modeled = {e.path for e in pm.entries}
        for path, src in sources:  # files the project model rejected: syntax errors
            if path not in modeled:
                findings.extend(analyze_source(src, path=path, select=select))
        for entry in pm.entries:
            if cache is not None:
                marks = marks_digest(pm.marks_fingerprint(entry))
                cached = cache.module_findings(entry.path, digests[entry.path], marks, select_key)
                if cached is not None:
                    findings.extend(Finding(**d) for d in cached)
                    continue
            module_findings = _filter_findings(
                run_rules(entry.tree, entry.lines, entry.path, model=entry.model),
                entry.lines, select,
            )
            if cache is not None:
                cache.set_module(
                    entry.path, digests[entry.path], marks, select_key,
                    [f.to_dict() for f in module_findings],
                )
            findings.extend(module_findings)
        # Whole-program concurrency pass (TPU021-TPU023): depends on every module at
        # once (thread roots in one file reach shared fields in another), so it is
        # recomputed on every tree-cache miss and NEVER stored in the per-module cache
        # — the tree-level entry above covers the all-files-unchanged fast path.
        from torchmetrics_tpu._lint.concurrency import run_concurrency_rules

        lines_by_path = {e.path: e.lines for e in pm.entries}
        conc = run_concurrency_rules(pm)
        by_path: Dict[str, List[Finding]] = {}
        for f in conc:
            by_path.setdefault(f.path, []).append(f)
        for cpath, group in by_path.items():
            findings.extend(_filter_findings(group, lines_by_path.get(cpath, []), select))
    else:
        for path, src in sources:
            findings.extend(analyze_source(src, path=path, select=select))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if cache is not None and tkey is not None:
        cache.set_tree(tkey, [f.to_dict() for f in findings])
        cache.save()
    LAST_RUN_STATS.update(
        runtime_ms=round((time.perf_counter() - t0) * 1e3, 2),
        cache_hits=cache.hits if cache is not None else 0,
        cache_misses=cache.misses if cache is not None else 0,
        files=len(sources), mode="project" if project else "per-module",
    )
    return findings


# ------------------------------------------------------------------------ output formats
def render_text(new: List[Finding], baselined: int, stale: List[Dict[str, Any]]) -> str:
    lines = [f.render() for f in new]
    per_rule: Dict[str, int] = {}
    for f in new:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    rule_part = ", ".join(f"{k}={v}" for k, v in sorted(per_rule.items())) or "none"
    lines.append(
        f"jaxlint: {len(new)} new finding(s) [{rule_part}], {baselined} baselined,"
        f" {len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    )
    for entry in stale:
        lines.append(
            f"  stale baseline entry: {entry['rule']} {entry['path']} :: {entry['fingerprint']!r}"
        )
    return "\n".join(lines)


def render_json(new: List[Finding], baselined: int, stale: List[Dict[str, Any]]) -> str:
    return json.dumps(
        {
            "tool": "jaxlint",
            "new": [f.to_dict() for f in new],
            "new_count": len(new),
            "baselined_count": baselined,
            "stale_baseline_entries": stale,
        },
        indent=2,
    )


def render_sarif(new: List[Finding], rule_index: Dict[str, str]) -> str:
    """Minimal SARIF 2.1.0 document (one run, one result per new finding)."""
    rules = [
        {"id": rid, "shortDescription": {"text": desc}}
        for rid, desc in sorted(rule_index.items())
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": "warning",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": f.line, "startColumn": f.col + 1},
                    }
                }
            ],
        }
        for f in new
    ]
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {"driver": {"name": "jaxlint", "rules": rules}},
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)


def _gh_escape(text: str, property_value: bool = False) -> str:
    """Escape per GitHub workflow-command rules (data vs property positions differ)."""
    text = text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    if property_value:
        text = text.replace(":", "%3A").replace(",", "%2C")
    return text


def render_github(new: List[Finding], baselined: int, stale: List[Dict[str, Any]]) -> str:
    """GitHub Actions annotations: one ``::warning`` workflow command per new finding.

    Printed to a job's stdout, each line becomes an inline annotation on the PR diff —
    no upload step, no SARIF processing delay (the SARIF export remains the archival
    format for code-scanning; this is the instant-feedback one).
    """
    lines = [
        f"::warning file={_gh_escape(f.path, True)},line={f.line},col={f.col + 1},"
        f"title={_gh_escape('jaxlint ' + f.rule, True)}::{_gh_escape(f.message)}"
        for f in new
    ]
    summary = (
        f"jaxlint: {len(new)} new finding(s), {baselined} baselined,"
        f" {len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    )
    lines.append(f"::notice title=jaxlint::{_gh_escape(summary)}" if not new
                 else f"::error title=jaxlint::{_gh_escape(summary)}")
    return "\n".join(lines)
