"""Whole-program analysis: package-wide symbol table, call graph, interprocedural marks.

The per-module pass (``rules._ModuleModel``) stops at file edges: a ``.item()`` inside a
helper called from a jit kernel two modules away, a donated buffer handed across a
function boundary, a ``jnp`` constant built in a utility reached from ``forward`` — all
invisible. This module builds the missing whole-program layer:

1. **Symbol table** — every module's top-level functions, plus its import map
   (``from m import f as g``, ``import pkg.mod as alias``, relative imports), resolved
   against the set of modules actually being analyzed. Names that resolve outside the
   project stay opaque (under-reporting beats guessing).
2. **Call-graph propagation to fixpoint** — four mark kinds flow along resolved calls
   (both intra- and cross-module):

   - *jit context*: callees reached from a jit-traced function are jit-traced, with the
     cross-module call path recorded as ``via`` (surfaced in finding messages);
   - *device parameters*: a parameter that receives a device/traced expression at some
     call site seeds the callee's traced-name dataflow even in eager context;
   - *hot paths*: callees reached from an eager per-step entry point (``update`` /
     ``forward``) are hot for TPU006 — except memoized helpers (``lru_cache``), whose
     constant builds are deliberate hoists;
   - *donating callables*: a parameter bound to a ``donate_argnums`` executable at a call
     site makes the callee a donation site for TPU012.

3. **Annotation seams** — defs carrying ``# jaxlint: donates(i, ...)`` or
   ``# jaxlint: donation-commit`` markers (``ops/dispatch.py``) are collected
   project-wide and attached to every module model, so TPU012 sees the engine's
   commit/recover protocol from any caller.

The pass only ADDS marks; a module analyzed alone (``analyze_source``) has none, which is
exactly the regression the project fixtures pin: single-module run misses the
cross-module hazard, project run reports it with a ``via:`` call path.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from torchmetrics_tpu._lint.rules import (
    _COMMIT_MARKER,
    _DONATES_RE,
    _HOT_EXACT,
    _HOT_PREFIXES,
    _TRACE_WRAPPERS,
    _FuncInfo,
    _ModuleModel,
    _aot_compile_donations,
    _donating_argnums,
    _dotted,
    _final_name,
    _is_device_expr,
    _scoped_walk,
)

#: decorators that memoize a function — its body runs once, so it is never "hot"
_MEMO_DECORATORS = frozenset({"lru_cache", "cache", "cached_property"})
#: propagation sweeps upper bound (call chains deeper than this are pathological)
_MAX_SWEEPS = 32


def module_name_of(display_path: str) -> str:
    """Dotted module name of a display path (``pkg/ops/dispatch.py`` → ``pkg.ops.dispatch``)."""
    parts = display_path[:-3].split("/") if display_path.endswith(".py") else display_path.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(p for p in parts if p)


class ModuleEntry:
    """One analyzed module: source facts plus its resolved import maps."""

    __slots__ = (
        "path", "name", "source", "lines", "tree", "model",
        "imports", "module_aliases", "base_jit",
    )

    def __init__(self, path: str, source: str, tree: ast.Module) -> None:
        self.path = path
        self.name = module_name_of(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.model = _ModuleModel(tree)
        #: local name -> (target module dotted name, symbol) for ``from M import sym``
        self.imports: Dict[str, Tuple[str, str]] = {}
        #: local alias -> target module dotted name for ``import M [as a]`` forms
        self.module_aliases: Dict[str, str] = {}
        #: qualnames jit-marked by the per-module pass alone (before propagation)
        self.base_jit: Set[str] = {f.qualname for f in self.model.functions if f.jit}

    @property
    def package(self) -> str:
        return self.name.rsplit(".", 1)[0] if "." in self.name else ""


class ProjectModel:
    """The whole-program model: modules, resolved imports, propagated marks."""

    def __init__(self, sources: Sequence[Tuple[str, str]]) -> None:
        self.entries: List[ModuleEntry] = []
        for path, source in sources:
            try:
                tree = ast.parse(source)
            except SyntaxError:
                continue  # the driver reports TPU000 for these; nothing to model
            self.entries.append(ModuleEntry(path, source, tree))
        self.by_module: Dict[str, ModuleEntry] = {e.name: e for e in self.entries}
        #: project-wide donation annotations (final def name -> donated positions)
        self.donators: Dict[str, Set[int]] = {}
        #: project-wide commit/recover seam names (`# jaxlint: donation-commit` defs)
        self.barriers: Set[str] = set()
        self._tn_cache: Dict[int, Tuple[Tuple, Tuple[Set[str], Set[str]]]] = {}
        for entry in self.entries:
            self._resolve_imports(entry)
        self._inherit_class_flags()
        for entry in self.entries:
            self._collect_annotations(entry)
        for entry in self.entries:  # rules read these off the model (getattr, default None)
            entry.model.project_donators = self.donators  # type: ignore[attr-defined]
            entry.model.project_barriers = self.barriers  # type: ignore[attr-defined]
        self._propagate()

    # ------------------------------------------------------------------ model construction
    def _resolve_imports(self, entry: ModuleEntry) -> None:
        for node in ast.walk(entry.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name in self.by_module:
                        local = alias.asname or alias.name.split(".")[0]
                        # ``import a.b.c`` binds ``a`` — only the asname form gives a
                        # direct handle on the submodule; the bare form is resolved at
                        # call sites through the dotted chain
                        if alias.asname is not None:
                            entry.module_aliases[local] = alias.name
                        else:
                            root = alias.name.split(".")[0]
                            if root in self.by_module:
                                entry.module_aliases[root] = root
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative import: climb from this module's package
                    pkg_parts = entry.name.split(".")[:-1]
                    climb = node.level - 1
                    if climb:
                        pkg_parts = pkg_parts[: len(pkg_parts) - climb] if climb <= len(pkg_parts) else []
                    base = ".".join(pkg_parts + ([node.module] if node.module else []))
                for alias in node.names:
                    local = alias.asname or alias.name
                    submodule = f"{base}.{alias.name}" if base else alias.name
                    if submodule in self.by_module:
                        entry.module_aliases[local] = submodule
                    elif base in self.by_module:
                        entry.imports[local] = (base, alias.name)

    def _resolve_base_flags(self, entry: ModuleEntry, base: ast.AST) -> Optional[Set[str]]:
        """``jit_*`` flags switched off by a base-class expression, resolved cross-module."""
        if isinstance(base, ast.Name):
            local = entry.imports.get(base.id)
            if local is not None:
                mod, sym = local
                target = self.by_module.get(mod)
                if target is not None:
                    return target.model.class_flags_off.get(sym)
            return entry.model.class_flags_off.get(base.id)
        d = _dotted(base)
        if d and len(d) >= 2 and d[0] in entry.module_aliases:
            modname = ".".join([entry.module_aliases[d[0]]] + d[1:-1])
            target = self.by_module.get(modname)
            if target is not None:
                return target.model.class_flags_off.get(d[-1])
        return None

    def _inherit_class_flags(self) -> None:
        """Merge ``jit_update``/``jit_compute`` opt-outs through IMPORTED base classes.

        The per-module pass inherits flags only along same-module bases; here the whole
        curve-metric family (``BinaryROC(BinaryPrecisionRecallCurve)`` etc.) picks up the
        base's ``jit_compute = False`` across the module boundary. Models of affected
        modules are REBUILT with the merged flags, so convention-jit marking — and every
        rule downstream of it — sees the true runtime contract instead of assuming the
        kernels trace.
        """
        extra: Dict[str, Dict[str, Set[str]]] = {}
        for _ in range(len(self.entries) + 1):
            changed = False
            for entry in self.entries:
                mod_extra = extra.setdefault(entry.path, {})
                for cname, cnode in entry.model.class_nodes.items():
                    have = entry.model.class_flags_off.get(cname, set()) | mod_extra.get(cname, set())
                    merged = set(have)
                    for base in cnode.bases:
                        bflags = self._resolve_base_flags(entry, base)
                        # same-module bases may themselves have gained imported flags
                        bname = _final_name(base)
                        if bname and bname in mod_extra:
                            bflags = (bflags or set()) | mod_extra[bname]
                        if bflags:
                            merged |= bflags
                    if merged != have:
                        mod_extra[cname] = merged
                        changed = True
            if not changed:
                break
        for entry in self.entries:
            mod_extra = {
                c: f for c, f in extra.get(entry.path, {}).items()
                if f - entry.model.class_flags_off.get(c, set())
            }
            if not mod_extra:
                continue
            entry.model = _ModuleModel(entry.tree, extra_flags_off=mod_extra)
            entry.base_jit = {f.qualname for f in entry.model.functions if f.jit}

    def _collect_annotations(self, entry: ModuleEntry) -> None:
        for info in entry.model.functions:
            dl = info.node.lineno
            src = entry.lines[dl - 1] if 0 < dl <= len(entry.lines) else ""
            m = _DONATES_RE.search(src)
            if m:
                self.donators[info.name] = {int(x) for x in m.group(1).split(",")}
            if _COMMIT_MARKER in src:
                self.barriers.add(info.name)

    # ------------------------------------------------------------------------- resolution
    def _lookup(self, module: str, symbol: str) -> List[Tuple[ModuleEntry, _FuncInfo]]:
        target = self.by_module.get(module)
        if target is None:
            return []
        return [(target, fi) for fi in target.model.by_name.get(symbol, []) if fi.cls is None]

    def resolve_call(
        self, entry: ModuleEntry, info: Optional[_FuncInfo], call: ast.Call
    ) -> List[Tuple[ModuleEntry, _FuncInfo]]:
        """Project functions a call site can reach (imported names, module attrs, locals)."""
        fn = call.func
        if isinstance(fn, ast.Name):
            tgt = self.imports_of(entry).get(fn.id)
            if tgt is not None:
                return self._lookup(*tgt)
            # intra-module plain call (same visibility rule as _propagate_jit)
            cands = entry.model.by_name.get(fn.id, [])
            cls = info.cls if info is not None else None
            return [(entry, fi) for fi in cands if fi.cls is None or fi.cls == cls]
        if isinstance(fn, ast.Attribute):
            d = _dotted(fn)
            if d is None:
                return []
            if len(d) == 2 and d[0] == "self" and info is not None and info.cls is not None:
                return [(entry, fi) for fi in entry.model.by_name.get(d[1], []) if fi.cls == info.cls]
            # alias.sym(...) — or a dotted module path ending in .sym(...)
            head = entry.module_aliases.get(d[0])
            if head is not None:
                modname = ".".join([head] + d[1:-1])
                return self._lookup(modname, d[-1])
            modname = ".".join(d[:-1])
            if modname in self.by_module:
                return self._lookup(modname, d[-1])
        return []

    def imports_of(self, entry: ModuleEntry) -> Dict[str, Tuple[str, str]]:
        return entry.imports

    # ------------------------------------------------------------------------ propagation
    def _traced_names(self, entry: ModuleEntry, info: _FuncInfo) -> Tuple[Set[str], Set[str]]:
        key = (info.jit, tuple(sorted(info.extra_traced)))
        cached = self._tn_cache.get(id(info))
        if cached is not None and cached[0] == key:
            return cached[1]
        result = entry.model.traced_names(info)
        self._tn_cache[id(info)] = (key, result)
        return result

    @staticmethod
    def _is_memoized(info: _FuncInfo) -> bool:
        for dec in info.node.decorator_list:
            name = _final_name(dec.func) if isinstance(dec, ast.Call) else _final_name(dec)
            if name in _MEMO_DECORATORS:
                return True
        return False

    @staticmethod
    def _is_name_hot(info: _FuncInfo) -> bool:
        return info.name in _HOT_EXACT or info.name.startswith(_HOT_PREFIXES)

    @staticmethod
    def _positional_params(info: _FuncInfo) -> List[str]:
        args = info.node.args
        return [a.arg for a in args.posonlyargs + args.args if a.arg not in ("self", "cls")]

    def _local_donators(self, entry: ModuleEntry, info: _FuncInfo) -> Dict[str, Set[int]]:
        """Names bound to donating callables inside ``info`` (literal jit/AOT + param marks)."""
        found: Dict[str, Set[int]] = {p: set(nums) for p, nums in info.donating_params.items()}
        for node in _scoped_walk(info.node):
            if not isinstance(node, ast.Assign):
                continue
            nums = _donating_argnums(node.value)
            if nums is None and isinstance(node.value, ast.Call) \
                    and _final_name(node.value.func) == "aot_compile":
                nums = _aot_compile_donations(node.value)
            if nums:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        found[t.id] = set(nums)
        return found

    def _propagate(self) -> None:
        # module-scope trace wrappers over imported functions: jax.jit(imported_fn, ...)
        for entry in self.entries:
            for node in ast.walk(entry.tree):
                if not (isinstance(node, ast.Call) and _final_name(node.func) in _TRACE_WRAPPERS):
                    continue
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name) and sub.id in entry.imports:
                        for tentry, tinfo in self._lookup(*entry.imports[sub.id]):
                            if not tinfo.jit:
                                # a direct wrap IS a root: every non-static param traces
                                tinfo.jit = tinfo.jit_root = True
                                tinfo.via = (f"{entry.path}::<wrap>",)
        for _ in range(_MAX_SWEEPS):
            if not self._sweep():
                break
        # re-run each module's intra-module jit closure so nested defs and plain local
        # calls inside newly-marked functions inherit the context (idempotent)
        for entry in self.entries:
            entry.model._propagate_jit()

    def _sweep(self) -> bool:
        changed = False
        for entry in self.entries:
            for info in entry.model.functions:
                calls = [n for n in _scoped_walk(info.node) if isinstance(n, ast.Call)]
                if not calls:
                    continue
                traced, jit_callables = self._traced_names(entry, info)
                donators = self._local_donators(entry, info)
                hot = (not info.jit) and (info.hot or self._is_name_hot(info))
                qual = f"{entry.path}::{info.qualname}"
                guard_spans = entry.model.config_guard_spans(info)
                for call in calls:
                    targets = self.resolve_call(entry, info, call)
                    if not targets:
                        continue
                    # config-gated (eager-by-contract) call sites never carry jit context
                    guarded = any(lo <= call.lineno <= hi for lo, hi in guard_spans)
                    for tentry, tinfo in targets:
                        if tinfo is info:
                            continue
                        # jit context flows caller -> callee
                        if info.jit and not tinfo.jit and not guarded:
                            tinfo.jit = True
                            tinfo.via = (info.via or ()) + (qual,)
                            changed = True
                        # hot (eager per-step) context, minus memoized helpers
                        if hot and not tinfo.jit and not tinfo.hot \
                                and not self._is_name_hot(tinfo) and not self._is_memoized(tinfo):
                            tinfo.hot = True
                            tinfo.hot_via = (info.hot_via or ()) + (qual,)
                            changed = True
                        params = self._positional_params(tinfo)
                        kwonly = {a.arg for a in tinfo.node.args.kwonlyargs}
                        # device values at call sites seed the callee's dataflow
                        for i, arg in enumerate(call.args):
                            if isinstance(arg, ast.Starred) or i >= len(params):
                                continue
                            p = params[i]
                            if p in tinfo.extra_traced or p in tinfo.static_params:
                                continue
                            if _is_device_expr(arg, traced, jit_callables):
                                tinfo.extra_traced.add(p)
                                changed = True
                        for kw in call.keywords:
                            if kw.arg is None or (kw.arg not in params and kw.arg not in kwonly):
                                continue
                            if kw.arg in tinfo.extra_traced or kw.arg in tinfo.static_params:
                                continue
                            if _is_device_expr(kw.value, traced, jit_callables):
                                tinfo.extra_traced.add(kw.arg)
                                changed = True
                        # donating callables handed across the boundary
                        for i, arg in enumerate(call.args):
                            if not (isinstance(arg, ast.Name) and arg.id in donators):
                                continue
                            if i >= len(params):
                                continue
                            p = params[i]
                            have = tinfo.donating_params.get(p, set())
                            want = donators[arg.id]
                            if not want <= have:
                                tinfo.donating_params[p] = have | want
                                if tinfo.via is None:
                                    tinfo.via = (info.via or ()) + (qual,)
                                changed = True
        return changed

    # ----------------------------------------------------------------------- fingerprints
    def marks_fingerprint(self, entry: ModuleEntry) -> str:
        """Stable digest input of every interprocedural mark affecting this module.

        A cached per-module finding list is valid iff the module's source digest AND this
        fingerprint both match — marks are pure functions of the whole tree, so equal
        fingerprints guarantee equal rule output for an unchanged file.
        """
        rows: List[str] = []
        for info in entry.model.functions:
            added_jit = info.jit and info.qualname not in entry.base_jit
            if not (added_jit or info.extra_traced or info.hot or info.donating_params):
                continue
            rows.append(
                f"{info.qualname}|jit={int(added_jit)}|via={','.join(info.via or ())}"
                f"|tr={','.join(sorted(info.extra_traced))}|hot={int(info.hot)}"
                f"|hv={','.join(info.hot_via or ())}"
                f"|don={sorted((p, tuple(sorted(n))) for p, n in info.donating_params.items())!r}"
            )
        rows.append(f"donators={sorted((k, tuple(sorted(v))) for k, v in self.donators.items())!r}")
        rows.append(f"barriers={sorted(self.barriers)!r}")
        return "\n".join(rows)
