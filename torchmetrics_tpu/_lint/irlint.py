"""Jaxpr IR backend: lint the kernels jax ACTUALLY compiles, cross-check the AST layer.

This is the one ``_lint`` component allowed to import jax (opt-in via ``--ir``; every
import is function-local so importing the module stays free). Where the AST rules reason
about source text, this backend lowers the registered ``_update``/``_compute`` kernels of
a target metric list to jaxprs — the compiler's ground truth — and lints the IR:

``IR001``  host callback primitive (``pure_callback``/``io_callback``/``debug_callback``)
           inside a compiled kernel — a per-step host round-trip the AST layer can only
           infer from names
``IR002``  explicit transfer primitive (``device_put`` with a host-flavored target)
           inside a compiled kernel
``IR003``  silent 64-bit upcast (``convert_element_type`` to f64/i64/u64/c128 from a
           narrower input) — the classic accidentally-enabled-x64 hazard that doubles
           HBM traffic on TPU

The **cross-check**: a kernel that FAILS to lower with a tracer/concretization error
contains a real host hazard (data-dependent branch, host coercion). If the engine jits
that kernel and the AST layer reported no finding inside its source span, that is an AST
false-negative — reported as its own finding class (``IR100``) so the static layer's
blind spots surface instead of silently under-reporting. Kernels the engine never traces
(``jit_update``/``jit_compute`` opt-outs) cannot disagree: whatever the hypothetical
lowering says, the runtime contract is eager, and the row is recorded as explained.
"""
from __future__ import annotations

import inspect
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

IR_RULES: Dict[str, str] = {
    "IR001": "host callback primitive inside a compiled kernel (per-step host round-trip)",
    "IR002": "transfer primitive inside a compiled kernel (device<->host copy per step)",
    "IR003": "silent 64-bit upcast inside a compiled kernel (x64 leak; 2x HBM on TPU)",
    "IR100": "AST false-negative: kernel cannot trace but the AST layer reported nothing",
}

#: the aggregation kernel set the acceptance gate pins (``--ir-metrics`` overrides)
DEFAULT_TARGETS: Tuple[str, ...] = ("SumMetric", "MeanMetric", "MaxMetric", "MinMetric", "CatMetric")

_CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback", "debug_callback", "callback", "outside_call"})
_TRANSFER_PRIMS = frozenset({"device_put"})
_WIDE_DTYPES = frozenset({"float64", "int64", "uint64", "complex128"})
#: error type names that mean "the python body needs a concrete value" — a host hazard,
#: as opposed to an infrastructure failure (no backend, bad example args)
_HAZARD_ERRORS = (
    "TracerBoolConversionError", "TracerArrayConversionError", "TracerIntegerConversionError",
    "ConcretizationTypeError", "UnexpectedTracerError",
)


def _iter_eqns(jaxpr: Any):
    """Yield every eqn of a (closed) jaxpr, descending into pjit/scan/cond sub-jaxprs."""
    raw = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in raw.eqns:
        yield eqn
        for pval in eqn.params.values():
            for sub in pval if isinstance(pval, (list, tuple)) else (pval,):
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    yield from _iter_eqns(sub)


def _lint_jaxpr(closed: Any, where: str) -> List[Dict[str, Any]]:
    findings: List[Dict[str, Any]] = []
    for eqn in _iter_eqns(closed):
        prim = eqn.primitive.name
        if prim in _CALLBACK_PRIMS:
            findings.append({
                "rule": "IR001", "where": where, "primitive": prim,
                "message": f"host callback `{prim}` compiled into {where} — one host"
                           " round-trip per execution; hoist the host work to the eager caller",
            })
        elif prim in _TRANSFER_PRIMS:
            device = eqn.params.get("devices") or eqn.params.get("device")
            findings.append({
                "rule": "IR002", "where": where, "primitive": prim,
                "message": f"transfer primitive `{prim}` (target={device!r}) compiled into"
                           f" {where} — a per-execution copy the kernel should not own",
            })
        elif prim == "convert_element_type":
            new = str(eqn.params.get("new_dtype", ""))
            srcs = [str(getattr(getattr(v, "aval", None), "dtype", "")) for v in eqn.invars]
            if new in _WIDE_DTYPES and all(s and s != new for s in srcs):
                findings.append({
                    "rule": "IR003", "where": where, "primitive": prim,
                    "message": f"silent upcast {srcs[0] or '?'} -> {new} compiled into {where}"
                               " — an x64 leak (2x HBM, halved vector width on TPU); pin the"
                               " dtype at the producer",
                })
    return findings


def _display_path(fp: str) -> str:
    parts = Path(fp).parts
    if "torchmetrics_tpu" in parts:
        return "/".join(parts[parts.index("torchmetrics_tpu"):])
    return Path(fp).name


def _kernel_span(fn: Any) -> Tuple[Optional[str], int, int]:
    """(display path, first line, last line) of a kernel's source definition."""
    try:
        src_lines, lo = inspect.getsourcelines(fn)
        fp = inspect.getsourcefile(fn)
    except (OSError, TypeError):
        return None, 0, 0
    return _display_path(fp or ""), lo, lo + len(src_lines) - 1


def _ast_hits(ast_findings: Optional[Sequence[Any]], path: Optional[str], lo: int, hi: int) -> List[Any]:
    if not ast_findings or path is None:
        return []
    return [f for f in ast_findings if f.path == path and lo <= f.line <= hi]


def _example_state(metric: Any):
    """Abstract-friendly example state: defaults for tensors, a flat f32 row per list state."""
    import jax.numpy as jnp

    state = dict(metric._state.tensors)
    for name in metric._state.lists:
        state[name] = jnp.ones((4,), jnp.float32)
    return state


def run_ir_lint(
    targets: Optional[Sequence[str]] = None,
    ast_findings: Optional[Sequence[Any]] = None,
    value_shape: Tuple[int, ...] = (8,),
) -> Dict[str, Any]:
    """Lower + lint the target metrics' kernels; cross-check against the AST findings.

    Returns a report dict: per-kernel rows (lowered / findings / verdict), the flat IR
    finding list, the AST false-negatives, and the unexplained disagreements (expected
    empty on the shipped tree — the self-check test pins exactly that).
    """
    report: Dict[str, Any] = {
        "backend": None, "kernels": [], "findings": [],
        "ast_false_negatives": [], "unexplained": [], "skipped": None,
    }
    try:
        import jax
        import jax.numpy as jnp

        report["backend"] = jax.default_backend()
    except Exception as err:  # no jax / no backend: the opt-in backend degrades to a no-op
        report["skipped"] = f"jax unavailable: {err!r}"
        return report

    import torchmetrics_tpu.aggregation as agg

    names = list(targets) if targets else list(DEFAULT_TARGETS)
    value = jnp.ones(value_shape, jnp.float32)
    for cname in names:
        cls = getattr(agg, cname, None)
        if cls is None:
            report["kernels"].append({
                "metric": cname, "kernel": "-", "lowered": False,
                "error": "unknown metric class", "verdict": "explained: unresolved target",
            })
            continue
        metric = cls()
        state = _example_state(metric)
        for kind, fn, flag in (
            ("update", metric._update, "jit_update"),
            ("compute", metric._compute, "jit_compute"),
        ):
            engine_jits = getattr(cls, flag, True)
            path, lo, hi = _kernel_span(fn)
            hits = _ast_hits(ast_findings, path, lo, hi)
            row: Dict[str, Any] = {
                "metric": cname, "kernel": kind, "path": path, "span": [lo, hi],
                "engine_jits": bool(engine_jits), "ast_findings": len(hits),
                "lowered": False, "error": None, "findings": [],
            }
            where = f"{cname}._{kind}"
            try:
                closed = jax.make_jaxpr(fn)(state, value) if kind == "update" \
                    else jax.make_jaxpr(fn)(state)
                row["lowered"] = True
                row["findings"] = _lint_jaxpr(closed, where)
                report["findings"].extend(row["findings"])
                if hits and engine_jits:
                    # AST flagged source the compiler traces cleanly — over-report
                    row["verdict"] = "unexplained: AST finding in a kernel that lowers clean"
                    report["unexplained"].append(row)
                else:
                    row["verdict"] = "agree"
            except Exception as err:
                row["error"] = f"{type(err).__name__}: {err}"
                hazard = type(err).__name__ in _HAZARD_ERRORS
                if not engine_jits:
                    row["verdict"] = f"explained: engine never traces this kernel ({flag}=False)"
                elif hazard and hits:
                    row["verdict"] = "agree"  # both layers see the hazard
                elif hazard:
                    fn_row = {
                        "rule": "IR100", "where": where, "path": path, "line": lo,
                        "message": f"{where} cannot trace ({type(err).__name__}) but the AST"
                                   " layer reported no finding in its span — a static-analysis"
                                   " blind spot; add or refine the covering rule",
                    }
                    report["ast_false_negatives"].append(fn_row)
                    row["verdict"] = "ast_false_negative"
                else:
                    row["verdict"] = "explained: lowering infrastructure error"
            report["kernels"].append(row)
    return report


def render_ir_report(report: Dict[str, Any]) -> str:
    if report.get("skipped"):
        return f"jaxlint-ir: skipped ({report['skipped']})"
    lines = [f"jaxlint-ir: backend={report['backend']}"]
    for row in report["kernels"]:
        status = "ok" if row.get("lowered") else "no-trace"
        lines.append(
            f"  {row['metric']}._{row['kernel']}: {status},"
            f" {len(row.get('findings', []))} IR finding(s),"
            f" {row.get('ast_findings', 0)} AST finding(s) in span -> {row.get('verdict')}"
        )
    for f in report["findings"]:
        lines.append(f"  {f['rule']} {f['where']}: {f['message']}")
    for f in report["ast_false_negatives"]:
        lines.append(f"  {f['rule']} {f['where']}: {f['message']}")
    lines.append(
        f"jaxlint-ir: {len(report['findings'])} IR finding(s),"
        f" {len(report['ast_false_negatives'])} AST false-negative(s),"
        f" {len(report['unexplained'])} unexplained disagreement(s)"
    )
    return "\n".join(lines)
