"""jaxlint CLI: ``python -m torchmetrics_tpu._lint [paths ...]``.

Exit codes: 0 clean (all findings baselined), 1 new findings (or stale baseline entries
under ``--strict-baseline``), 2 usage error. ``--write-baseline`` regenerates the baseline
from the current finding set and always exits 0.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from torchmetrics_tpu._lint.baseline import (
    DEFAULT_BASELINE_PATH,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from torchmetrics_tpu._lint.core import analyze_paths, render_json, render_sarif, render_text
from torchmetrics_tpu._lint.rules import RULES


def _default_paths() -> List[str]:
    """Prefer a source checkout's ``torchmetrics_tpu/`` in cwd; else the installed package."""
    if Path("torchmetrics_tpu").is_dir():
        return ["torchmetrics_tpu"]
    return [str(Path(__file__).resolve().parent.parent)]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchmetrics_tpu._lint",
        description="jaxlint: AST-based JAX/TPU hazard analyzer (rules TPU001-TPU008)",
    )
    parser.add_argument("paths", nargs="*", help="files/directories to lint (default: the package)")
    parser.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    parser.add_argument(
        "--baseline", default=str(DEFAULT_BASELINE_PATH),
        help="baseline file of waived findings; pass 'none' to disable (default: the shipped baseline)",
    )
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from the current finding set and exit 0")
    parser.add_argument("--strict-baseline", action="store_true",
                        help="also fail on stale baseline entries (the CI mode)")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--list-rules", action="store_true", help="print the rule registry and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}  {RULES[rid]}")
        return 0

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in RULES]
        if unknown:
            print(f"jaxlint: unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"jaxlint: path(s) not found: {', '.join(missing)}", file=sys.stderr)
        return 2

    findings = analyze_paths(paths, select=select)

    if args.write_baseline:
        target = DEFAULT_BASELINE_PATH if args.baseline == "none" else Path(args.baseline)
        payload = write_baseline(findings, target)
        print(f"jaxlint: wrote {len(payload['entries'])} baseline entr"
              f"{'y' if len(payload['entries']) == 1 else 'ies'} to {target}")
        return 0

    entries = [] if args.baseline == "none" else load_baseline(args.baseline)
    new, waived, stale = apply_baseline(findings, entries)

    if args.format == "json":
        print(render_json(new, waived, stale))
    elif args.format == "sarif":
        print(render_sarif(new, RULES))
    else:
        print(render_text(new, waived, stale))

    if new:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
