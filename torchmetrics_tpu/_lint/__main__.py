"""jaxlint CLI: ``python -m torchmetrics_tpu._lint [paths ...]``.

Exit codes: 0 clean (all findings baselined), 1 new findings (or stale baseline entries
under ``--strict-baseline``; or IR findings/disagreements under ``--ir``), 2 usage error.
``--write-baseline`` regenerates the baseline from the current finding set and always
exits 0.

The default run is the whole-program pass (interprocedural marks, ``via:`` call paths);
``--no-project`` restores the legacy per-module view. ``--cache`` enables the
content-fingerprint incremental cache (``make jaxlint`` uses it), ``--ir`` additionally
runs the opt-in jaxpr IR backend over the registered aggregation kernels and cross-checks
it against the AST layer.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from torchmetrics_tpu._lint.baseline import (
    DEFAULT_BASELINE_PATH,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from torchmetrics_tpu._lint.cache import DEFAULT_CACHE_PATH, LintCache
from torchmetrics_tpu._lint.core import (
    analyze_paths,
    render_github,
    render_json,
    render_sarif,
    render_text,
)
from torchmetrics_tpu._lint.rules import RULE_META, RULES


def _changed_paths(ref: str) -> Optional[List[str]]:
    """Repo-relative ``.py`` paths changed vs. ``ref`` (None when git is unusable).

    Finding display paths are rooted at the linted root's basename, which matches the
    repo-relative paths ``git diff`` prints when jaxlint runs from the repo root — the
    ``make jaxlint-fast`` layout. Untracked files count as changed (``--others``): a
    brand-new module must not dodge the fast gate.
    """
    import subprocess

    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--", "*.py"],
            capture_output=True, text=True, timeout=30, check=True,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard", "--", "*.py"],
            capture_output=True, text=True, timeout=30, check=True,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    out = diff.stdout.splitlines() + untracked.stdout.splitlines()
    return sorted({line.strip() for line in out if line.strip()})


def _default_paths() -> List[str]:
    """Prefer a source checkout's ``torchmetrics_tpu/`` in cwd; else the installed package."""
    if Path("torchmetrics_tpu").is_dir():
        return ["torchmetrics_tpu"]
    return [str(Path(__file__).resolve().parent.parent)]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m torchmetrics_tpu._lint",
        description="jaxlint: whole-program AST JAX/TPU hazard analyzer (rules TPU000-TPU023)",
    )
    parser.add_argument("paths", nargs="*", help="files/directories to lint (default: the package)")
    parser.add_argument("--format", choices=("text", "json", "sarif", "github"), default="text")
    parser.add_argument("--output", default=None, metavar="PATH",
                        help="also write the rendered output to this file (e.g. a SARIF artifact)")
    parser.add_argument(
        "--baseline", default=str(DEFAULT_BASELINE_PATH),
        help="baseline file of waived findings; pass 'none' to disable (default: the shipped baseline)",
    )
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline from the current finding set and exit 0")
    parser.add_argument("--strict-baseline", action="store_true",
                        help="also fail on stale baseline entries (the CI mode)")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule ids to run (default: all)")
    parser.add_argument("--no-project", action="store_true",
                        help="per-module analysis only (no interprocedural propagation;"
                             " skips the TPU021-TPU023 concurrency pass)")
    parser.add_argument("--changed-only", default=None, metavar="GIT_REF",
                        help="report only findings in files changed vs. GIT_REF (the"
                             " analysis still sees the whole program, so cross-module"
                             " rules stay sound — only the REPORT is diff-scoped)")
    parser.add_argument("--cache", nargs="?", const=DEFAULT_CACHE_PATH, default=None,
                        metavar="PATH",
                        help="incremental cache file (default location when given bare:"
                             f" {DEFAULT_CACHE_PATH}; env TM_TPU_LINT_CACHE also honored)")
    parser.add_argument("--ir", action="store_true",
                        help="also run the jaxpr IR backend over the registered aggregation"
                             " kernels and cross-check it against the AST layer (imports jax)")
    parser.add_argument("--ir-metrics", default=None,
                        help="comma-separated metric class names for --ir (default:"
                             " Sum/Mean/Max/Min/Cat)")
    parser.add_argument("--write-rule-catalog", nargs="?", const="docs/static-analysis.md",
                        default=None, metavar="DOCS",
                        help="regenerate the rule-catalog table in the docs file and exit")
    parser.add_argument("--list-rules", action="store_true", help="print the rule registry and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid}  [{RULE_META[rid]['severity']}]  {RULES[rid]}")
        return 0

    if args.write_rule_catalog is not None:
        from torchmetrics_tpu._lint.catalog import sync_docs

        changed = sync_docs(args.write_rule_catalog, write=True)
        print(f"jaxlint: rule catalog in {args.write_rule_catalog}"
              f" {'updated' if changed else 'already in sync'}")
        return 0

    select = None
    if args.select:
        select = [r.strip() for r in args.select.split(",") if r.strip()]
        unknown = [r for r in select if r not in RULES]
        if unknown:
            print(f"jaxlint: unknown rule id(s): {', '.join(unknown)}", file=sys.stderr)
            return 2

    paths = args.paths or _default_paths()
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"jaxlint: path(s) not found: {', '.join(missing)}", file=sys.stderr)
        return 2

    if args.changed_only and args.write_baseline:
        print("jaxlint: --changed-only cannot combine with --write-baseline"
              " (a diff-scoped finding set would silently drop baseline entries)",
              file=sys.stderr)
        return 2

    cache = LintCache(args.cache) if args.cache else None
    findings = analyze_paths(paths, select=select, project=not args.no_project, cache=cache)

    if args.changed_only:
        changed = _changed_paths(args.changed_only)
        if changed is None:
            print(f"jaxlint: --changed-only {args.changed_only}: git diff failed;"
                  " reporting the full finding set", file=sys.stderr)
        else:
            changed_set = set(changed)
            findings = [f for f in findings if f.path in changed_set]
            print(f"jaxlint: --changed-only {args.changed_only}:"
                  f" {len(changed_set)} changed .py file(s) in scope", file=sys.stderr)

    if args.write_baseline:
        target = DEFAULT_BASELINE_PATH if args.baseline == "none" else Path(args.baseline)
        payload = write_baseline(findings, target)
        print(f"jaxlint: wrote {len(payload['entries'])} baseline entr"
              f"{'y' if len(payload['entries']) == 1 else 'ies'} to {target}")
        return 0

    entries = [] if args.baseline == "none" else load_baseline(args.baseline)
    new, waived, stale = apply_baseline(findings, entries)

    if args.format == "json":
        rendered = render_json(new, waived, stale)
    elif args.format == "sarif":
        rendered = render_sarif(new, RULES)
    elif args.format == "github":
        rendered = render_github(new, waived, stale)
    else:
        rendered = render_text(new, waived, stale)
    print(rendered)
    if args.output:
        Path(args.output).write_text(rendered + "\n", encoding="utf-8")

    rc = 0
    if new:
        rc = 1
    elif stale and args.strict_baseline:
        rc = 1

    if args.ir:
        from torchmetrics_tpu._lint.irlint import render_ir_report, run_ir_lint

        targets = None
        if args.ir_metrics:
            targets = [t.strip() for t in args.ir_metrics.split(",") if t.strip()]
        report = run_ir_lint(targets=targets, ast_findings=findings)
        print(render_ir_report(report))
        if report["findings"] or report["ast_false_negatives"] or report["unexplained"]:
            rc = rc or 1

    return rc


if __name__ == "__main__":
    sys.exit(main())
