"""Nominal module metrics (reference ``src/torchmetrics/nominal/``)."""
from torchmetrics_tpu.nominal.metrics import (
    CramersV,
    FleissKappa,
    PearsonsContingencyCoefficient,
    TheilsU,
    TschuprowsT,
)

__all__ = [
    "CramersV",
    "FleissKappa",
    "PearsonsContingencyCoefficient",
    "TheilsU",
    "TschuprowsT",
]
