"""Stateful nominal metrics (reference ``src/torchmetrics/nominal/*.py``).

State: one (C, C) confusion-matrix tensor with ``dist_reduce_fx="sum"`` (reference
``nominal/cramers.py:105``) — fixed shape, jitted MXU one-hot update, psum-syncable. Fleiss'
kappa keeps a counts list state with ``"cat"`` (reference ``nominal/fleiss_kappa.py:88``).
"""
from __future__ import annotations

from typing import Any, Dict, Literal, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.nominal.cramers import _cramers_v_compute, _cramers_v_update
from torchmetrics_tpu.functional.nominal.fleiss_kappa import _fleiss_kappa_compute, _fleiss_kappa_update
from torchmetrics_tpu.functional.nominal.pearson import (
    _pearsons_contingency_coefficient_compute,
    _pearsons_contingency_coefficient_update,
)
from torchmetrics_tpu.functional.nominal.theils_u import _theils_u_compute, _theils_u_update
from torchmetrics_tpu.functional.nominal.tschuprows import _tschuprows_t_compute, _tschuprows_t_update
from torchmetrics_tpu.functional.nominal.utils import _nominal_input_validation
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat


class _ConfmatNominalMetric(Metric):
    """Shared shell: (C, C) confmat sum-state + trace-safe compute."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        num_classes: int,
        nan_strategy: Literal["replace", "drop"] = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(num_classes, int) and num_classes > 0):
            raise ValueError(f"Argument `num_classes` should be a positive integer, got {num_classes}.")
        _nominal_input_validation(nan_strategy, nan_replace_value)
        self.num_classes = num_classes
        self.nan_strategy = nan_strategy
        self.nan_replace_value = nan_replace_value
        self.add_state("confmat", jnp.zeros((num_classes, num_classes), jnp.float32), dist_reduce_fx="sum")

    def _update_fn(self, preds, target) -> Array:
        raise NotImplementedError

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        return {"confmat": state["confmat"] + self._update_fn(preds, target)}


class CramersV(_ConfmatNominalMetric):
    """Cramer's V (reference ``nominal/cramers.py:28``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.nominal import CramersV
        >>> metric = CramersV(num_classes=3)
        >>> metric.update(np.array([0, 1, 2, 0, 1]), np.array([0, 1, 2, 0, 2]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.5000
    """

    def __init__(
        self,
        num_classes: int,
        bias_correction: bool = True,
        nan_strategy: Literal["replace", "drop"] = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, nan_strategy=nan_strategy, nan_replace_value=nan_replace_value, **kwargs)
        self.bias_correction = bias_correction

    def _update_fn(self, preds, target):
        return _cramers_v_update(preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value)

    def _compute(self, state):
        return _cramers_v_compute(state["confmat"], self.bias_correction)


class PearsonsContingencyCoefficient(_ConfmatNominalMetric):
    """Pearson's contingency coefficient (reference ``nominal/pearson.py:28``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.nominal import PearsonsContingencyCoefficient
        >>> metric = PearsonsContingencyCoefficient(num_classes=3)
        >>> metric.update(np.array([0, 1, 2, 0, 1]), np.array([0, 1, 2, 0, 2]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.7454
    """

    def _update_fn(self, preds, target):
        return _pearsons_contingency_coefficient_update(
            preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value
        )

    def _compute(self, state):
        return _pearsons_contingency_coefficient_compute(state["confmat"])


class TheilsU(_ConfmatNominalMetric):
    """Theil's U (reference ``nominal/theils_u.py:28``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.nominal import TheilsU
        >>> metric = TheilsU(num_classes=3)
        >>> metric.update(np.array([0, 1, 2, 0, 1]), np.array([0, 1, 2, 0, 2]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.7372
    """

    def _update_fn(self, preds, target):
        return _theils_u_update(preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value)

    def _compute(self, state):
        return _theils_u_compute(state["confmat"])


class TschuprowsT(_ConfmatNominalMetric):
    """Tschuprow's T (reference ``nominal/tschuprows.py:28``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.nominal import TschuprowsT
        >>> metric = TschuprowsT(num_classes=3)
        >>> metric.update(np.array([0, 1, 2, 0, 1]), np.array([0, 1, 2, 0, 2]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.5000
    """

    def __init__(
        self,
        num_classes: int,
        bias_correction: bool = True,
        nan_strategy: Literal["replace", "drop"] = "replace",
        nan_replace_value: Optional[float] = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes, nan_strategy=nan_strategy, nan_replace_value=nan_replace_value, **kwargs)
        self.bias_correction = bias_correction

    def _update_fn(self, preds, target):
        return _tschuprows_t_update(preds, target, self.num_classes, self.nan_strategy, self.nan_replace_value)

    def _compute(self, state):
        return _tschuprows_t_compute(state["confmat"], self.bias_correction)


class FleissKappa(Metric):
    """Fleiss' kappa (reference ``nominal/fleiss_kappa.py:28``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.nominal import FleissKappa
        >>> metric = FleissKappa(mode='counts')
        >>> metric.update(np.array([[3, 2, 5], [4, 4, 2], [5, 3, 2]]))
        >>> print(f"{float(metric.compute()):.4f}")
        -0.0550
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, mode: Literal["counts", "probs"] = "counts", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if mode not in ("counts", "probs"):
            raise ValueError("Argument ``mode`` must be one of 'counts' or 'probs'.")
        self.mode = mode
        self.add_state("counts", default=[], dist_reduce_fx="cat")

    def _update(self, state: Dict[str, Any], ratings: Array) -> Dict[str, Any]:
        return {"counts": _fleiss_kappa_update(ratings, self.mode)}

    def _compute(self, state: Dict[str, Any]) -> Array:
        counts = state["counts"] if not isinstance(state["counts"], list) else dim_zero_cat(state["counts"])
        return _fleiss_kappa_compute(counts)
