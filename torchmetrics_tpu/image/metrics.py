"""Image module metrics, conv/reduction family (reference ``src/torchmetrics/image/*.py``).

Each class is a thin stateful shell over the jitted functional kernels in
``torchmetrics_tpu.functional.image``; state layouts mirror the reference exactly (scalar
sum-states for streaming metrics, cat list-states where the algorithm needs the full data).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.image.d_lambda import (
    _spectral_distortion_index_check_inputs,
    _spectral_distortion_index_compute,
)
from torchmetrics_tpu.functional.image.ergas import _ergas_check_inputs, _ergas_compute
from torchmetrics_tpu.functional.image.psnr import _psnr_compute, _psnr_update
from torchmetrics_tpu.functional.image.psnrb import _psnrb_compute, _psnrb_update
from torchmetrics_tpu.functional.image.rase import relative_average_spectral_error
from torchmetrics_tpu.functional.image.rmse_sw import _rmse_sw_update
from torchmetrics_tpu.functional.image.sam import _sam_check_inputs, _sam_compute
from torchmetrics_tpu.functional.image.ssim import (
    _multiscale_ssim_update,
    _ssim_check_inputs,
    _ssim_update,
)
from torchmetrics_tpu.functional.image.tv import _total_variation_compute, _total_variation_update
from torchmetrics_tpu.functional.image.uqi import _uqi_check_inputs, _uqi_compute
from torchmetrics_tpu.functional.image.vif import _vif_per_image_channel
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.prints import rank_zero_warn


class StructuralSimilarityIndexMeasure(Metric):
    """SSIM (reference ``image/ssim.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import StructuralSimilarityIndexMeasure
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> target = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> metric = StructuralSimilarityIndexMeasure(data_range=1.0)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        -0.0864
    """

    higher_is_better = True
    is_differentiable = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        gaussian_kernel: bool = True,
        sigma: Union[float, Sequence[float]] = 1.5,
        kernel_size: Union[int, Sequence[int]] = 11,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        return_full_image: bool = False,
        return_contrast_sensitivity: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        if reduction in ("elementwise_mean", "sum"):
            self.add_state("similarity", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        else:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        if return_contrast_sensitivity or return_full_image:
            self.add_state("image_return", [], dist_reduce_fx="cat")
        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.return_full_image = return_full_image
        self.return_contrast_sensitivity = return_contrast_sensitivity

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        preds, target = _ssim_check_inputs(preds, target)
        pack = _ssim_update(
            preds, target, self.gaussian_kernel, self.sigma, self.kernel_size,
            self.data_range, self.k1, self.k2, self.return_full_image, self.return_contrast_sensitivity,
        )
        similarity, image = pack if isinstance(pack, tuple) else (pack, None)
        out: Dict[str, Array] = {}
        if image is not None:
            out["image_return"] = image
        if self.reduction in ("elementwise_mean", "sum"):
            out["similarity"] = state["similarity"] + jnp.sum(similarity)
            out["total"] = state["total"] + preds.shape[0]
        else:
            out["similarity"] = similarity
            out["total"] = state["total"] + preds.shape[0]
        return out

    def _compute(self, state: Dict[str, Any]):
        if self.reduction == "elementwise_mean":
            similarity = state["similarity"] / state["total"]
        elif self.reduction == "sum":
            similarity = state["similarity"]
        else:
            similarity = state["similarity"]
        if self.return_contrast_sensitivity or self.return_full_image:
            return similarity, state["image_return"]
        return similarity


class MultiScaleStructuralSimilarityIndexMeasure(Metric):
    """MS-SSIM (reference ``image/ssim.py:220``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import MultiScaleStructuralSimilarityIndexMeasure
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(1, 1, 48, 48).astype(np.float32)
        >>> target = rng.rand(1, 1, 48, 48).astype(np.float32)
        >>> metric = MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0, betas=(0.5, 0.5))
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.0258
    """

    higher_is_better = True
    is_differentiable = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        gaussian_kernel: bool = True,
        kernel_size: Union[int, Sequence[int]] = 11,
        sigma: Union[float, Sequence[float]] = 1.5,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
        normalize: Optional[str] = "relu",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_reduction = ("elementwise_mean", "sum", "none", None)
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        if reduction in ("elementwise_mean", "sum"):
            self.add_state("similarity", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        else:
            self.add_state("similarity", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        if not (isinstance(kernel_size, (Sequence, int))):
            raise ValueError("Argument `kernel_size` expected to be an sequence or an int")
        if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
            raise ValueError("Argument `betas` is expected to be a tuple of floats.")
        if normalize and normalize not in ("relu", "simple"):
            raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.betas = betas
        self.normalize = normalize

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        preds, target = _ssim_check_inputs(preds, target)
        similarity = _multiscale_ssim_update(
            preds, target, self.gaussian_kernel, self.sigma, self.kernel_size,
            self.data_range, self.k1, self.k2, self.betas, self.normalize,
        )
        if self.reduction in ("elementwise_mean", "sum"):
            return {
                "similarity": state["similarity"] + jnp.sum(similarity),
                "total": state["total"] + preds.shape[0],
            }
        return {"similarity": similarity, "total": state["total"] + preds.shape[0]}

    def _compute(self, state: Dict[str, Any]) -> Array:
        if self.reduction == "elementwise_mean":
            return state["similarity"] / state["total"]
        return state["similarity"]


class PeakSignalNoiseRatio(Metric):
    """PSNR (reference ``image/psnr.py:27``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import PeakSignalNoiseRatio
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> target = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> metric = PeakSignalNoiseRatio(data_range=1.0)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        7.0466
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        data_range: Optional[Union[float, Tuple[float, float]]] = None,
        base: float = 10.0,
        reduction: Optional[str] = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if dim is None and reduction != "elementwise_mean":
            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
        if dim is None:
            self.add_state("sum_squared_error", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
            self.add_state("total", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", [], dist_reduce_fx="cat")
            self.add_state("total", [], dist_reduce_fx="cat")

        self.clamping_range: Optional[Tuple[float, float]] = None
        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.data_range_val = None
            # track the observed target range (reference psnr.py:110-115, incl. its zero-init)
            self.add_state("min_target", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="min")  # jaxlint: disable=TPU005 — reference-parity zero-init (torch psnr.py:110-115); diverging would change upstream numerics
            self.add_state("max_target", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="max")  # jaxlint: disable=TPU005 — reference-parity zero-init, see min_target
        elif isinstance(data_range, tuple):
            self.clamping_range = (float(data_range[0]), float(data_range[1]))
            self.data_range_val = float(data_range[1] - data_range[0])
        else:
            self.data_range_val = float(data_range)
        self.base = base
        self.reduction = reduction
        self.dim = tuple(dim) if isinstance(dim, Sequence) else dim

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        preds = jnp.asarray(preds, jnp.float32)
        target = jnp.asarray(target, jnp.float32)
        if self.clamping_range is not None:
            preds = jnp.clip(preds, *self.clamping_range)
            target = jnp.clip(target, *self.clamping_range)
        sum_squared_error, num_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            out = {
                "sum_squared_error": state["sum_squared_error"] + sum_squared_error,
                "total": state["total"] + num_obs,
            }
            if self.data_range_val is None:
                out["min_target"] = jnp.minimum(jnp.min(target), state["min_target"])
                out["max_target"] = jnp.maximum(jnp.max(target), state["max_target"])
            return out
        return {"sum_squared_error": sum_squared_error.reshape(-1), "total": num_obs.reshape(-1)}

    def _compute(self, state: Dict[str, Any]) -> Array:
        if self.data_range_val is not None:
            data_range = jnp.asarray(self.data_range_val, jnp.float32)
        else:
            data_range = state["max_target"] - state["min_target"]
        return _psnr_compute(
            state["sum_squared_error"], state["total"], data_range, base=self.base, reduction=self.reduction
        )


class PeakSignalNoiseRatioWithBlockedEffect(Metric):
    """PSNR-B (reference ``image/psnrb.py:33``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import PeakSignalNoiseRatioWithBlockedEffect
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> target = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> metric = PeakSignalNoiseRatioWithBlockedEffect(block_size=8)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        7.0466
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, block_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(block_size, int) or block_size < 1:
            raise ValueError("Argument `block_size` should be a positive integer")
        self.block_size = block_size
        self.add_state("sum_squared_error", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("bef", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("data_range", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="max")  # jaxlint: disable=TPU005 — observed ranges are nonnegative by construction, so 0 IS the max identity here

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        sum_squared_error, bef, num_obs = _psnrb_update(preds, target, block_size=self.block_size)
        return {
            "sum_squared_error": state["sum_squared_error"] + sum_squared_error,
            "bef": state["bef"] + bef,
            "total": state["total"] + num_obs,
            "data_range": jnp.maximum(
                state["data_range"], jnp.max(jnp.asarray(target, jnp.float32)) - jnp.min(jnp.asarray(target, jnp.float32))
            ),
        }

    def _compute(self, state: Dict[str, Any]) -> Array:
        return _psnrb_compute(state["sum_squared_error"], state["bef"], state["total"], state["data_range"])


class UniversalImageQualityIndex(Metric):
    """UQI (reference ``image/uqi.py:32``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import UniversalImageQualityIndex
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> target = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> metric = UniversalImageQualityIndex()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        -0.0921
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if reduction is None or reduction == "none":
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
        else:
            self.add_state("sum_uqi", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
            self.add_state("numel", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.kernel_size = tuple(kernel_size)
        self.sigma = tuple(sigma)
        self.reduction = reduction

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        preds, target = _uqi_check_inputs(preds, target)
        if self.reduction is None or self.reduction == "none":
            return {"preds": preds, "target": target}
        uqi_score = _uqi_compute(preds, target, self.kernel_size, self.sigma, reduction="sum")
        ps = preds.shape
        n = ps[0] * ps[1] * (ps[2] - self.kernel_size[0] + 1) * (ps[3] - self.kernel_size[1] + 1)
        return {"sum_uqi": state["sum_uqi"] + uqi_score, "numel": state["numel"] + n}

    def _compute(self, state: Dict[str, Any]) -> Array:
        if self.reduction is None or self.reduction == "none":
            return _uqi_compute(state["preds"], state["target"], self.kernel_size, self.sigma, self.reduction)
        return state["sum_uqi"] / state["numel"] if self.reduction == "elementwise_mean" else state["sum_uqi"]


class SpectralAngleMapper(Metric):
    """SAM (reference ``image/sam.py:34``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import SpectralAngleMapper
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(1, 3, 16, 16).astype(np.float32)
        >>> target = rng.rand(1, 3, 16, 16).astype(np.float32)
        >>> metric = SpectralAngleMapper()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.6319
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction is None or reduction == "none":
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
        else:
            self.add_state("sum_sam", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
            self.add_state("numel", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.reduction = reduction

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        preds, target = _sam_check_inputs(preds, target)
        if self.reduction is None or self.reduction == "none":
            return {"preds": preds, "target": target}
        sam_score = _sam_compute(preds, target, reduction="sum")
        ps = preds.shape
        return {"sum_sam": state["sum_sam"] + sam_score, "numel": state["numel"] + ps[0] * ps[2] * ps[3]}

    def _compute(self, state: Dict[str, Any]) -> Array:
        if self.reduction is None or self.reduction == "none":
            return _sam_compute(state["preds"], state["target"], self.reduction)
        return state["sum_sam"] / state["numel"] if self.reduction == "elementwise_mean" else state["sum_sam"]


class ErrorRelativeGlobalDimensionlessSynthesis(Metric):
    """ERGAS (reference ``image/ergas.py:32``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import ErrorRelativeGlobalDimensionlessSynthesis
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> target = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> metric = ErrorRelativeGlobalDimensionlessSynthesis()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.1f}")
        331.2
    """

    higher_is_better = False
    is_differentiable = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, ratio: float = 4, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")
        self.ratio = ratio
        self.reduction = reduction

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        preds, target = _ergas_check_inputs(preds, target)
        return {"preds": preds, "target": target}

    def _compute(self, state: Dict[str, Any]) -> Array:
        return _ergas_compute(state["preds"], state["target"], self.ratio, self.reduction)


class RelativeAverageSpectralError(Metric):
    """RASE (reference ``image/rase.py:28``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import RelativeAverageSpectralError
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> target = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> metric = RelativeAverageSpectralError()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.1f}")
        5278.6
    """

    higher_is_better = False
    is_differentiable = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError(f"Argument `window_size` must be a positive integer, but got {window_size}")
        self.window_size = window_size
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        return {"preds": jnp.asarray(preds, jnp.float32), "target": jnp.asarray(target, jnp.float32)}

    def _compute(self, state: Dict[str, Any]) -> Array:
        return relative_average_spectral_error(state["preds"], state["target"], self.window_size)


class RootMeanSquaredErrorUsingSlidingWindow(Metric):
    """Sliding-window RMSE (reference ``image/rmse_sw.py:29``).

    The reference also carries a lazily-created ``rmse_map`` buffer that its ``compute`` never
    returns (``image/rmse_sw.py:82-95``); only the scalar accumulators are kept here.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import RootMeanSquaredErrorUsingSlidingWindow
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> target = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> metric = RootMeanSquaredErrorUsingSlidingWindow(window_size=8)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.4485
    """

    higher_is_better = False
    is_differentiable = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, window_size: int = 8, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(window_size, int) or window_size < 1:
            raise ValueError('Argument `window_size` must be a positive integer.')
        self.window_size = window_size
        self.add_state("rmse_val_sum", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total_images", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        rmse_val_sum, _, total_images = _rmse_sw_update(
            preds, target, self.window_size,
            rmse_val_sum=state["rmse_val_sum"], rmse_map=None, total_images=state["total_images"],
        )
        return {"rmse_val_sum": rmse_val_sum, "total_images": total_images}

    def _compute(self, state: Dict[str, Any]) -> Array:
        return state["rmse_val_sum"] / state["total_images"]


class SpectralDistortionIndex(Metric):
    """D-lambda (reference ``image/d_lambda.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import SpectralDistortionIndex
        >>> rng = np.random.RandomState(42)
        >>> preds = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> target = rng.rand(2, 3, 32, 32).astype(np.float32)
        >>> metric = SpectralDistortionIndex()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.0404
    """

    higher_is_better = True
    is_differentiable = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, p: int = 1, reduction: str = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(p, int) or p <= 0:
            raise ValueError(f"`p` must be a positive integer. Got p: {p}.")
        valid_reduction = ("elementwise_mean", "sum", "none")
        if reduction not in valid_reduction:
            raise ValueError(f"Expected argument `reduction` be one of {valid_reduction} but got {reduction}")
        self.p = p
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        preds, target = _spectral_distortion_index_check_inputs(preds, target)
        return {"preds": preds, "target": target}

    def _compute(self, state: Dict[str, Any]) -> Array:
        return _spectral_distortion_index_compute(state["preds"], state["target"], self.p, self.reduction)


class TotalVariation(Metric):
    """Total variation (reference ``image/tv.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import TotalVariation
        >>> rng = np.random.RandomState(42)
        >>> img = rng.rand(1, 1, 16, 16).astype(np.float32)
        >>> metric = TotalVariation()
        >>> metric.update(img)
        >>> print(f"{float(metric.compute()):.1f}")
        162.0
    """

    full_state_update = False
    is_differentiable = True
    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if reduction is not None and reduction not in ("sum", "mean", "none"):
            raise ValueError("Argument `reduction` must be either 'sum', 'mean', 'none' or None")
        self.reduction = reduction
        # list state only in 'none' mode, so sum/mean sweeps keep the fused update_batches path
        if reduction is None or reduction == "none":
            self.add_state("score_list", [], dist_reduce_fx="cat")
        else:
            self.add_state("score", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("num_elements", jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")  # jaxlint: disable=TPU005 — counts batch entries (img.shape[0]), a sample-scale quantity far below 2^31; int32 is the TPU count dtype

    def _update(self, state: Dict[str, Array], img: Array) -> Dict[str, Array]:
        score, num_elements = _total_variation_update(img)
        out: Dict[str, Array] = {"num_elements": state["num_elements"] + num_elements}
        if self.reduction is None or self.reduction == "none":
            out["score_list"] = score
        else:
            out["score"] = state["score"] + jnp.sum(score)
        return out

    def _compute(self, state: Dict[str, Any]) -> Array:
        if self.reduction is None or self.reduction == "none":
            score = state["score_list"]
            if isinstance(score, list):
                score = dim_zero_cat(score) if score else jnp.zeros((0,))
        else:
            score = state["score"]
        return _total_variation_compute(score, state["num_elements"], self.reduction)


class VisualInformationFidelity(Metric):
    """VIF-p (reference ``image/vif.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import VisualInformationFidelity
        >>> rng = np.random.RandomState(7)
        >>> preds = rng.rand(1, 1, 48, 48).astype(np.float32)
        >>> target = rng.rand(1, 1, 48, 48).astype(np.float32)
        >>> metric = VisualInformationFidelity()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.0031
    """

    is_differentiable = True
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, sigma_n_sq: float = 2.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(sigma_n_sq, (float, int)) or sigma_n_sq < 0:
            raise ValueError(f"Argument `sigma_n_sq` must be a positive float or int, but got {sigma_n_sq}")
        self.add_state("vif_score", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.sigma_n_sq = sigma_n_sq

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        preds = jnp.asarray(preds, jnp.float32)
        target = jnp.asarray(target, jnp.float32)
        n, c, h, w = preds.shape
        p = jnp.moveaxis(preds, 1, 0).reshape(c * n, 1, h, w)
        t = jnp.moveaxis(target, 1, 0).reshape(c * n, 1, h, w)
        per = _vif_per_image_channel(p, t, self.sigma_n_sq).reshape(c, n)
        # mean over channels per image, then sum over the batch (reference image/vif.py:71-79)
        vif_per_image = jnp.mean(per, axis=0) if c > 1 else per.reshape(-1)
        return {"vif_score": state["vif_score"] + jnp.sum(vif_per_image), "total": state["total"] + n}

    def _compute(self, state: Dict[str, Any]) -> Array:
        return state["vif_score"] / state["total"]
