"""Image module metrics (reference ``src/torchmetrics/image/``)."""
from torchmetrics_tpu.image.metrics import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    PeakSignalNoiseRatioWithBlockedEffect,
    RelativeAverageSpectralError,
    RootMeanSquaredErrorUsingSlidingWindow,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    TotalVariation,
    UniversalImageQualityIndex,
    VisualInformationFidelity,
)

__all__ = [
    "ErrorRelativeGlobalDimensionlessSynthesis",
    "MultiScaleStructuralSimilarityIndexMeasure",
    "PeakSignalNoiseRatio",
    "PeakSignalNoiseRatioWithBlockedEffect",
    "RelativeAverageSpectralError",
    "RootMeanSquaredErrorUsingSlidingWindow",
    "SpectralAngleMapper",
    "SpectralDistortionIndex",
    "StructuralSimilarityIndexMeasure",
    "TotalVariation",
    "UniversalImageQualityIndex",
    "VisualInformationFidelity",
]
