"""Generative-model image metrics: FID, KID, IS, MiFID, LPIPS, PPL.

Reference: ``src/torchmetrics/image/{fid,kid,inception,mifid,lpip,perceptual_path_length}.py``.

TPU redesign decisions (SURVEY §7, VERDICT r2 item 2):

- **Pluggable feature extractors.** The reference hard-depends on torch-fidelity's pretrained
  InceptionV3 (``fid.py:44-66``); this build has no network egress and no bundled weights, so
  every metric accepts ``feature`` as a *callable* ``imgs -> (N, d)`` (any JAX/host function —
  e.g. a flax InceptionV3, a CLIP tower, or a host-callback into torch) or ``None`` (inputs to
  ``update`` are already extracted features). Passing the reference's integer layer ids raises
  the same ``ModuleNotFoundError`` contract the reference raises without torch-fidelity.
- **f32 cancellation-free covariance states** instead of the reference's fp64 sums
  (``fid.py:314-320``): per-batch *centered* Gram matrices (exact, small magnitudes) plus a
  batch-mean outer-product accumulator. ``cov = cov_centered_sum + mu_outer_sum - n·μμᵀ`` only
  cancels in the O(μ²) term, not in the dominant second moment — TPUs have no fast fp64, so
  this is the hardware-honest equivalent. All states stay ``psum``-able.
- **TPU-compilable matrix sqrt**: ``tr((Σ₁Σ₂)^½)`` via two symmetric eigendecompositions
  (``tr((S Σ₂ S)^½)`` with ``S = Σ₁^½`` from ``eigh``) — the reference's non-symmetric
  ``torch.linalg.eigvals`` (``fid.py:159-180``) has no TPU lowering.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat

FeatureExtractor = Optional[Callable[[Array], Array]]

_INCEPTION_LAYERS = (64, 192, 768, 2048)


_METRIC_DISPLAY = {
    "FrechetInceptionDistance": "FrechetInceptionDistance",
    "KernelInceptionDistance": "Kernel Inception Distance",
    "InceptionScore": "InceptionScore",
    "MemorizationInformedFrechetInceptionDistance": "MemorizationInformedFrechetInceptionDistance",
}


def _resolve_extractor(
    feature: Union[int, str, FeatureExtractor],
    metric_name: str,
    valid_strs: Tuple[str, ...] = (),
) -> Tuple[FeatureExtractor, Optional[int]]:
    """Map the ``feature`` argument to (extractor, num_features-if-known).

    Integer inputs (and the strings in ``valid_strs``, e.g. InceptionScore's
    ``"logits_unbiased"``) resolve through the host-delegation adapter
    (``utils/pretrained.py``) to torch-fidelity's InceptionV3 when installed — the reference's
    out-of-the-box default (``image/fid.py:44-66``) — and raise the reference's exact
    ``ModuleNotFoundError`` otherwise.
    """
    if feature is None:
        return None, None
    if isinstance(feature, (int, str)) and not callable(feature):
        if isinstance(feature, int) and feature not in _INCEPTION_LAYERS:
            raise ValueError(
                f"Integer input to argument `feature` must be one of {_INCEPTION_LAYERS}, but got {feature}."
            )
        if isinstance(feature, str) and feature not in valid_strs:
            raise ValueError(
                f"String input to argument `feature` must be one of {list(valid_strs) or '(no strings accepted)'},"
                f" but got {feature!r}."
            )
        from torchmetrics_tpu.utils.pretrained import inception_feature_extractor

        display = _METRIC_DISPLAY.get(metric_name, metric_name)
        num_features = feature if isinstance(feature, int) else None
        return inception_feature_extractor(feature, display), num_features
    if callable(feature):
        return feature, None
    raise TypeError("Got unknown input to argument `feature`")


def _sqrtm_trace_product(sigma1: Array, sigma2: Array) -> Array:
    """``tr((Σ₁ Σ₂)^{1/2})`` for symmetric PSD inputs via two ``eigh`` factorisations."""
    evals1, evecs1 = jnp.linalg.eigh(sigma1)
    sqrt1 = (evecs1 * jnp.sqrt(jnp.clip(evals1, 0.0))) @ evecs1.T
    inner = sqrt1 @ sigma2 @ sqrt1
    evals = jnp.linalg.eigvalsh(inner)
    return jnp.sum(jnp.sqrt(jnp.clip(evals, 0.0)))


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array) -> Array:
    """Fréchet distance between two gaussians (reference ``fid.py:159-180``)."""
    a = jnp.sum(jnp.square(mu1 - mu2))
    b = jnp.trace(sigma1) + jnp.trace(sigma2)
    c = _sqrtm_trace_product(sigma1, sigma2)
    return a + b - 2 * c


class _FeatureStatsMetric(Metric):
    """Shared machinery: extractor resolution + real/fake dispatch (host-side ``real`` flag)."""

    jit_update = False  # extractor may be arbitrary host code; `real` is a static branch
    # forward() must route through the overridden update() (full-state path) so the feature
    # extractor runs; the reduce-state fast path calls _update with raw images
    full_state_update = True

    def __init__(
        self,
        feature: Union[int, str, FeatureExtractor],
        reset_real_features: bool = True,
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.extractor, self._num_features_hint = _resolve_extractor(feature, type(self).__name__)
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize

    def _extract(self, imgs: Array) -> Array:
        if self.extractor is not None:
            if self.normalize:  # [0,1] floats -> uint8 [0,255], the extractor contract (fid.py:324)
                imgs = (jnp.asarray(imgs) * 255).astype(jnp.uint8)
            feats = self.extractor(imgs)
        else:
            feats = jnp.asarray(imgs)
        feats = jnp.asarray(feats, jnp.float32)
        if feats.ndim == 1:
            feats = feats[None]
        return feats

    def update(self, imgs: Array, real: bool = True) -> None:  # noqa: D102
        super().update(self._extract(imgs), bool(real))

    def update_batches(self, imgs: Array, real: bool = True) -> None:
        """Per-batch loop: the host-side extractor and static `real` flag preclude a lax.scan sweep."""
        for i in range(jnp.shape(imgs)[0]):
            self.update(imgs[i], real=real)

    def reset(self) -> None:
        """Keep real-distribution statistics across resets when configured (reference ``fid.py:355-366``)."""
        if not self.reset_real_features:
            keep_t = {k: v for k, v in self._state.tensors.items() if k.startswith("real_")}
            keep_l = {k: list(v) for k, v in self._state.lists.items() if k.startswith("real_")}
            super().reset()
            self._state.tensors.update(keep_t)
            self._state.lists.update(keep_l)
        else:
            super().reset()


def _kahan_add(total: Array, comp: Array, contribution: Array) -> Tuple[Array, Array]:
    """Neumaier compensated add: ``(total, comp) += contribution`` in effective ~double-f32.

    The compensation buffer carries the low-order bits every f32 add drops; the corrected value
    is ``total + comp``. Both buffers are plain sums, so ``dist_reduce_fx="sum"`` stays valid.
    """
    t = total + contribution
    comp = comp + jnp.where(
        jnp.abs(total) >= jnp.abs(contribution),
        (total - t) + contribution,
        (contribution - t) + total,
    )
    return t, comp


class FrechetInceptionDistance(_FeatureStatsMetric):
    """FID (reference ``image/fid.py:182``).

    States are f32 streaming moments: per-distribution ``n``, feature sum, centered-Gram sum and
    batch-mean outer-product sum — see the module docstring for why this replaces the
    reference's fp64 raw second-moment sums. Every accumulator is Neumaier-compensated
    (``_kahan_add``), recovering near-fp64 effective precision on TPUs that have no fast fp64:
    streaming-vs-fp64-oracle parity holds at ≤1e-4 (the reference stores fp64 sums instead,
    ``fid.py:314-320``).

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from torchmetrics_tpu.image import FrechetInceptionDistance
        >>> def feat(imgs):  # any callable imgs -> (N, d) features works
        ...     x = jnp.asarray(imgs, jnp.float32) / 255.0
        ...     return x.reshape(x.shape[0], 3, -1).mean(-1)
        >>> rng = np.random.RandomState(0)
        >>> real = rng.randint(0, 200, (16, 3, 8, 8)).astype(np.uint8)
        >>> fake = rng.randint(50, 255, (16, 3, 8, 8)).astype(np.uint8)
        >>> metric = FrechetInceptionDistance(feature=feat)
        >>> metric.update(real, real=True)
        >>> metric.update(fake, real=False)
        >>> print(f"{float(metric.compute()):.4f}")
        0.1311
    """

    higher_is_better = False
    is_differentiable = False
    full_state_update = True  # forward() must route through the extractor-running update()
    plot_lower_bound = 0.0
    jit_compute = False  # host-side sample-count guard; eigh still runs on device

    def __init__(
        self,
        feature: Union[int, str, FeatureExtractor] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        num_features: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(feature, reset_real_features, normalize, **kwargs)
        if num_features is None:
            if self._num_features_hint is not None:
                num_features = self._num_features_hint
            elif self.extractor is None:
                raise ValueError("`num_features` must be given when `feature` is None (raw-feature mode).")
            else:
                num_features = int(np.asarray(self.extractor(jnp.zeros((1, 3, 299, 299), jnp.float32))).shape[-1])
        d = num_features
        for prefix in ("real", "fake"):
            self.add_state(f"{prefix}_features_sum", jnp.zeros((d,), jnp.float32), dist_reduce_fx="sum")
            self.add_state(f"{prefix}_features_sum_comp", jnp.zeros((d,), jnp.float32), dist_reduce_fx="sum")
            self.add_state(f"{prefix}_features_cov_sum", jnp.zeros((d, d), jnp.float32), dist_reduce_fx="sum")
            self.add_state(f"{prefix}_features_cov_sum_comp", jnp.zeros((d, d), jnp.float32), dist_reduce_fx="sum")
            self.add_state(f"{prefix}_mu_outer_sum", jnp.zeros((d, d), jnp.float32), dist_reduce_fx="sum")
            self.add_state(f"{prefix}_mu_outer_sum_comp", jnp.zeros((d, d), jnp.float32), dist_reduce_fx="sum")
            self.add_state(f"{prefix}_features_num_samples", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")

    def _update(self, state: Dict[str, Array], features: Array, real: Array) -> Dict[str, Array]:
        prefix = "real" if bool(real) else "fake"
        n = features.shape[0]
        bmean = jnp.mean(features, axis=0)
        centered = features - bmean
        out = {}
        for name, contribution in (
            ("features_sum", jnp.sum(features, axis=0)),
            ("features_cov_sum", jnp.matmul(centered.T, centered, precision="highest")),
            ("mu_outer_sum", n * jnp.outer(bmean, bmean)),
        ):
            total, comp = _kahan_add(
                state[f"{prefix}_{name}"], state[f"{prefix}_{name}_comp"], contribution
            )
            out[f"{prefix}_{name}"] = total
            out[f"{prefix}_{name}_comp"] = comp
        out[f"{prefix}_features_num_samples"] = state[f"{prefix}_features_num_samples"] + n
        return out

    @staticmethod
    def _stats(state: Dict[str, Array], prefix: str) -> Tuple[Array, Array]:
        n = state[f"{prefix}_features_num_samples"]

        def _corrected(name: str) -> Array:
            return state[f"{prefix}_{name}"] + state[f"{prefix}_{name}_comp"]

        mu = _corrected("features_sum") / n
        cov_num = (
            _corrected("features_cov_sum")
            + _corrected("mu_outer_sum")
            - n * jnp.outer(mu, mu)
        )
        return mu, cov_num / (n - 1)

    def _compute(self, state: Dict[str, Any]) -> Array:
        if float(state["real_features_num_samples"]) < 2 or float(state["fake_features_num_samples"]) < 2:
            raise RuntimeError(
                "More than one sample is required for both the real and fake distributed to compute FID"
            )
        mu_r, cov_r = self._stats(state, "real")
        mu_f, cov_f = self._stats(state, "fake")
        return _compute_fid(mu_r, cov_r, mu_f, cov_f)


def _poly_kernel(f1: Array, f2: Array, degree: int, gamma: Optional[float], coef: float) -> Array:
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (f1 @ f2.T * gamma + coef) ** degree


def _poly_mmd(f_real: Array, f_fake: Array, degree: int, gamma: Optional[float], coef: float) -> Array:
    """Unbiased polynomial-kernel MMD² (reference ``kid.py:34-70``) — three MXU matmuls."""
    k_11 = _poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = _poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = _poly_kernel(f_real, f_fake, degree, gamma, coef)
    m = k_11.shape[0]
    kt_xx_sum = jnp.sum(k_11) - jnp.trace(k_11)
    kt_yy_sum = jnp.sum(k_22) - jnp.trace(k_22)
    k_xy_sum = jnp.sum(k_12)
    return (kt_xx_sum + kt_yy_sum) / (m * (m - 1)) - 2 * k_xy_sum / (m**2)


class KernelInceptionDistance(_FeatureStatsMetric):
    """KID (reference ``image/kid.py:70``): subset-resampled polynomial MMD over feature lists.

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from torchmetrics_tpu.image import KernelInceptionDistance
        >>> def feat(imgs):
        ...     x = jnp.asarray(imgs, jnp.float32) / 255.0
        ...     return x.reshape(x.shape[0], 3, -1).mean(-1)
        >>> rng = np.random.RandomState(0)
        >>> real = rng.randint(0, 200, (16, 3, 8, 8)).astype(np.uint8)
        >>> fake = rng.randint(50, 255, (16, 3, 8, 8)).astype(np.uint8)
        >>> metric = KernelInceptionDistance(feature=feat, subsets=2, subset_size=16)
        >>> metric.update(real, real=True)
        >>> metric.update(fake, real=False)
        >>> kid_mean, kid_std = metric.compute()
        >>> print(f"{float(kid_mean):.4f}")
        0.2825
    """

    higher_is_better = False
    is_differentiable = False
    full_state_update = True  # forward() must route through the extractor-running update()
    plot_lower_bound = 0.0
    jit_compute = False  # host loop over random subsets; kernels run on device

    def __init__(
        self,
        feature: Union[int, str, FeatureExtractor] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        normalize: bool = False,
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(feature, reset_real_features, normalize, **kwargs)
        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        # seeded subset resampling (reference uses the ambient torch RNG, kid.py:265-268)
        self.seed = seed
        self.add_state("real_features", [], dist_reduce_fx=None)
        self.add_state("fake_features", [], dist_reduce_fx=None)

    def _update(self, state: Dict[str, Array], features: Array, real: Array) -> Dict[str, Array]:
        return {("real_features" if bool(real) else "fake_features"): features}

    def _compute(self, state: Dict[str, Any]) -> Tuple[Array, Array]:
        real_features = state["real_features"]
        fake_features = state["fake_features"]
        if isinstance(real_features, list) or isinstance(fake_features, list):
            raise RuntimeError("No real/fake features accumulated; call `update` before `compute`.")
        n_real, n_fake = real_features.shape[0], fake_features.shape[0]
        if n_real < self.subset_size or n_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        rng = np.random.RandomState(self.seed)
        scores = []
        for _ in range(self.subsets):
            f_real = real_features[rng.permutation(n_real)[: self.subset_size]]
            f_fake = fake_features[rng.permutation(n_fake)[: self.subset_size]]
            scores.append(_poly_mmd(f_real, f_fake, self.degree, self.gamma, self.coef))
        kid = jnp.stack(scores)
        return jnp.mean(kid), jnp.std(kid)


class InceptionScore(Metric):
    """IS (reference ``image/inception.py:34``): exp KL between conditional and marginal label dists.

    ``feature`` must be a callable producing *logits* ``(N, num_classes)`` (the reference's
    default is the InceptionV3 ``logits_unbiased`` head) or ``None`` for pre-extracted logits.

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from torchmetrics_tpu.image import InceptionScore
        >>> def feat(imgs):  # stands in for the logits head
        ...     x = jnp.asarray(imgs, jnp.float32) / 255.0
        ...     return x.reshape(x.shape[0], 3, -1).mean(-1)
        >>> rng = np.random.RandomState(0)
        >>> imgs = rng.randint(0, 200, (16, 3, 8, 8)).astype(np.uint8)
        >>> metric = InceptionScore(feature=feat, splits=1)
        >>> metric.update(imgs)
        >>> score_mean, score_std = metric.compute()
        >>> print(f"{float(score_mean):.4f}")
        1.0002
    """

    higher_is_better = True
    is_differentiable = False
    full_state_update = True  # forward() must run the overridden update() (extractor)
    plot_lower_bound = 0.0
    jit_update = False
    jit_compute = False  # host-side permutation + python chunking

    def __init__(
        self,
        feature: Union[int, str, FeatureExtractor] = "logits_unbiased",
        splits: int = 10,
        normalize: bool = False,
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.extractor, _ = _resolve_extractor(feature, type(self).__name__, valid_strs=("logits_unbiased",))
        if not isinstance(normalize, bool):
            raise ValueError("Argument `normalize` expected to be a bool")
        self.normalize = normalize
        self.splits = splits
        self.seed = seed
        self.add_state("features", [], dist_reduce_fx=None)

    def update(self, imgs: Array) -> None:  # noqa: D102
        if self.extractor is not None:
            if self.normalize:
                imgs = (jnp.asarray(imgs) * 255).astype(jnp.uint8)
            feats = self.extractor(imgs)
        else:
            feats = jnp.asarray(imgs)
        super().update(jnp.asarray(feats, jnp.float32))

    def update_batches(self, imgs: Array) -> None:
        """Per-batch loop (host-side extractor + list state preclude the scan sweep)."""
        for i in range(jnp.shape(imgs)[0]):
            self.update(imgs[i])

    def _update(self, state: Dict[str, Array], features: Array) -> Dict[str, Array]:
        return {"features": features}

    def _compute(self, state: Dict[str, Any]) -> Tuple[Array, Array]:
        features = state["features"]
        if isinstance(features, list):
            raise RuntimeError("No features accumulated; call `update` before `compute`.")
        rng = np.random.RandomState(self.seed)
        features = features[rng.permutation(features.shape[0])]
        log_prob = jax.nn.log_softmax(features, axis=1)
        prob = jnp.exp(log_prob)
        # torch.chunk split sizes: ceil(N/splits) per chunk (inception.py:162-163)
        n = features.shape[0]
        chunk = -(-n // self.splits)
        kl_scores = []
        for start in range(0, n, chunk):
            p = prob[start : start + chunk]
            log_p = log_prob[start : start + chunk]
            mean_p = jnp.mean(p, axis=0, keepdims=True)
            kl = jnp.sum(p * (log_p - jnp.log(mean_p)), axis=1)
            kl_scores.append(jnp.exp(jnp.mean(kl)))
        kl = jnp.stack(kl_scores)
        return jnp.mean(kl), jnp.std(kl, ddof=1)


def _cosine_distance(features1: Array, features2: Array, eps: float = 0.1) -> Array:
    """Mean minimal cosine distance with the MiFID threshold rule (reference ``mifid.py:36-47``)."""
    f1 = features1[np.asarray(jnp.sum(features1, axis=1)) != 0]
    f2 = features2[np.asarray(jnp.sum(features2, axis=1)) != 0]
    f1 = f1 / jnp.linalg.norm(f1, axis=1, keepdims=True)
    f2 = f2 / jnp.linalg.norm(f2, axis=1, keepdims=True)
    d = 1.0 - jnp.abs(f1 @ f2.T)
    mean_min_d = jnp.mean(jnp.min(d, axis=1))
    return jnp.where(mean_min_d < eps, mean_min_d, jnp.ones_like(mean_min_d))


class MemorizationInformedFrechetInceptionDistance(_FeatureStatsMetric):
    """MiFID (reference ``image/mifid.py:66``): FID penalised by train-set memorisation.

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from torchmetrics_tpu.image import MemorizationInformedFrechetInceptionDistance
        >>> def feat(imgs):
        ...     x = jnp.asarray(imgs, jnp.float32) / 255.0
        ...     return x.reshape(x.shape[0], 3, -1).mean(-1)
        >>> rng = np.random.RandomState(0)
        >>> real = rng.randint(0, 200, (16, 3, 8, 8)).astype(np.uint8)
        >>> fake = rng.randint(50, 255, (16, 3, 8, 8)).astype(np.uint8)
        >>> metric = MemorizationInformedFrechetInceptionDistance(feature=feat)
        >>> metric.update(real, real=True)
        >>> metric.update(fake, real=False)
        >>> print(f"{float(metric.compute()):.4f}")
        257.8099
    """

    higher_is_better = False
    is_differentiable = False
    full_state_update = True  # forward() must route through the extractor-running update()
    plot_lower_bound = 0.0
    jit_compute = False

    def __init__(
        self,
        feature: Union[int, str, FeatureExtractor] = 2048,
        reset_real_features: bool = True,
        normalize: bool = False,
        cosine_distance_eps: float = 0.1,
        **kwargs: Any,
    ) -> None:
        super().__init__(feature, reset_real_features, normalize, **kwargs)
        if not (isinstance(cosine_distance_eps, float) and 1 >= cosine_distance_eps > 0):
            raise ValueError("Argument `cosine_distance_eps` expected to be a float greater than 0 and less than 1")
        self.cosine_distance_eps = cosine_distance_eps
        self.add_state("real_features", [], dist_reduce_fx=None)
        self.add_state("fake_features", [], dist_reduce_fx=None)

    def _update(self, state: Dict[str, Array], features: Array, real: Array) -> Dict[str, Array]:
        return {("real_features" if bool(real) else "fake_features"): features}

    def _compute(self, state: Dict[str, Any]) -> Array:
        real, fake = state["real_features"], state["fake_features"]
        if isinstance(real, list) or isinstance(fake, list):
            raise RuntimeError("No real/fake features accumulated; call `update` before `compute`.")
        mu_r, cov_r = jnp.mean(real, axis=0), jnp.cov(real, rowvar=False)
        mu_f, cov_f = jnp.mean(fake, axis=0), jnp.cov(fake, rowvar=False)
        fid = _compute_fid(mu_r, jnp.atleast_2d(cov_r), mu_f, jnp.atleast_2d(cov_f))
        # reference arg order is (real, fake): mean over REAL of min distance to fake
        # (mifid.py:36-47 called from compute() with real_features first)
        distance = _cosine_distance(real, fake, self.cosine_distance_eps)
        return jnp.where(fid > 1e-8, fid / (distance + 1e-14), jnp.zeros_like(fid))


class LearnedPerceptualImagePatchSimilarity(Metric):
    """LPIPS (reference ``image/lpip.py:40``).

    ``net`` must be a callable ``(img1, img2) -> (N,)`` per-image distances (a flax/JAX port of
    the learned AlexNet/VGG distance, or a host callback). The reference's pretrained
    ``net_type`` strings raise the same no-weights contract as the FID extractor.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.image import LearnedPerceptualImagePatchSimilarity
        >>> metric = LearnedPerceptualImagePatchSimilarity(net_type='alex')  # doctest: +SKIP
        >>> img1 = np.random.rand(2, 3, 64, 64).astype(np.float32) * 2 - 1
        >>> img2 = np.random.rand(2, 3, 64, 64).astype(np.float32) * 2 - 1
        >>> metric.update(img1, img2)  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
    """

    higher_is_better = False
    is_differentiable = True
    full_state_update = False
    plot_lower_bound = 0.0
    jit_update = False

    def __init__(
        self,
        net_type: Union[str, Callable[[Array, Array], Array]] = "alex",
        reduction: str = "mean",
        normalize: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if isinstance(net_type, str):
            valid_net_type = ("vgg", "alex", "squeeze")
            if net_type not in valid_net_type:
                raise ValueError(f"Argument `net_type` must be one of {valid_net_type}, but got {net_type}.")
            from torchmetrics_tpu.utils.pretrained import lpips_network

            net_type = lpips_network(net_type)
        if not callable(net_type):
            raise ValueError("Argument `net_type` must be a string or callable")
        self.net = net_type
        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.reduction = reduction
        if not isinstance(normalize, bool):
            raise ValueError(f"Argument `normalize` must be an bool but got {normalize}")
        self.normalize = normalize
        self.add_state("sum_scores", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")

    def _update(self, state: Dict[str, Array], img1: Array, img2: Array) -> Dict[str, Array]:
        if self.normalize:  # [0,1] -> [-1,1], the learned nets' expected domain (lpips.py:382-385)
            img1 = 2 * img1 - 1
            img2 = 2 * img2 - 1
        loss = jnp.asarray(self.net(img1, img2), jnp.float32).reshape(-1)
        return {
            "sum_scores": state["sum_scores"] + jnp.sum(loss),
            "total": state["total"] + loss.shape[0],
        }

    def _compute(self, state: Dict[str, Any]) -> Array:
        if self.reduction == "mean":
            return state["sum_scores"] / state["total"]
        return state["sum_scores"]


def _interpolate_latents(latents1: Array, latents2: Array, epsilon: float, method: str) -> Array:
    """Latent-path interpolation (reference ``functional/image/perceptual_path_length.py:109-152``)."""
    eps = 1e-7
    if latents1.shape != latents2.shape:
        raise ValueError("Latents must have the same shape.")
    if method == "lerp":
        return latents1 + (latents2 - latents1) * epsilon
    if method in ("slerp_any", "slerp_unit"):
        l1n = latents1 / jnp.clip(jnp.linalg.norm(latents1, axis=-1, keepdims=True), eps)
        l2n = latents2 / jnp.clip(jnp.linalg.norm(latents2, axis=-1, keepdims=True), eps)
        d = jnp.sum(l1n * l2n, axis=-1, keepdims=True)
        degenerate = (d > 1 - eps) | (d < -1 + eps)
        omega = jnp.arccos(jnp.clip(d, -1.0, 1.0))
        denom = jnp.clip(jnp.sin(omega), eps)
        out = (jnp.sin((1 - epsilon) * omega) / denom) * latents1 + (jnp.sin(epsilon * omega) / denom) * latents2
        lerp = latents1 + (latents2 - latents1) * epsilon
        out = jnp.where(degenerate, lerp, out)
        if method == "slerp_unit":
            out = out / jnp.clip(jnp.linalg.norm(out, axis=-1, keepdims=True), eps)
        return out
    raise ValueError(f"Interpolation method {method} not supported. Choose from 'lerp', 'slerp_any', 'slerp_unit'.")


def perceptual_path_length(
    generator: Any,
    num_samples: int = 10_000,
    conditional: bool = False,
    batch_size: int = 64,
    interpolation_method: str = "lerp",
    epsilon: float = 1e-4,
    lower_discard: Optional[float] = 0.01,
    upper_discard: Optional[float] = 0.99,
    sim_net: Optional[Callable[[Array, Array], Array]] = None,
    seed: int = 0,
) -> Tuple[Array, Array, Array]:
    """Perceptual path length of a generator (reference ``functional/image/perceptual_path_length.py:155``).

    ``generator`` needs ``sample(num_samples) -> (N, z)`` latents and ``__call__(z[, labels])``
    producing images scaled to [0, 255]; ``sim_net`` is a required ``(img1, img2) -> (N,)``
    perceptual distance (the reference defaults to pretrained LPIPS-vgg, unavailable here).
    """
    if sim_net is None:
        raise ModuleNotFoundError(
            "perceptual_path_length requires a similarity net; pretrained LPIPS weights are not bundled"
            " in this build — pass `sim_net` as a callable `(img1, img2) -> (N,)`."
        )
    if not hasattr(generator, "sample") or not callable(generator.sample):
        raise NotImplementedError(
            "The generator must have a `sample` method with signature `sample(num_samples: int) -> Tensor` where the"
            " returned tensor has shape `(num_samples, z_size)`."
        )
    if conditional and not hasattr(generator, "num_classes"):
        raise AttributeError("The generator must have a `num_classes` attribute when `conditional=True`.")
    if not (isinstance(num_samples, int) and num_samples > 0):
        raise ValueError(f"Argument `num_samples` must be a positive integer, but got {num_samples}.")
    if not (isinstance(epsilon, float) and epsilon > 0):
        raise ValueError(f"Argument `epsilon` must be a positive float, but got {epsilon}.")
    if not (isinstance(batch_size, int) and batch_size > 0):
        raise ValueError(f"Argument `batch_size` must be a positive integer, but got {batch_size}.")
    if lower_discard is not None and not (isinstance(lower_discard, float) and 0 <= lower_discard <= 1):
        raise ValueError(
            f"Argument `lower_discard` must be a float between 0 and 1 or `None`, but got {lower_discard}."
        )
    if upper_discard is not None and not (isinstance(upper_discard, float) and 0 <= upper_discard <= 1):
        raise ValueError(
            f"Argument `upper_discard` must be a float between 0 and 1 or `None`, but got {upper_discard}."
        )

    rng = np.random.RandomState(seed)
    latent1 = jnp.asarray(generator.sample(num_samples))
    latent2 = jnp.asarray(generator.sample(num_samples))
    latent2 = _interpolate_latents(latent1, latent2, epsilon, interpolation_method)
    labels = jnp.asarray(rng.randint(0, generator.num_classes, (num_samples,))) if conditional else None

    distances = []
    for i in range(math.ceil(num_samples / batch_size)):
        sl = slice(i * batch_size, (i + 1) * batch_size)
        z = jnp.concatenate((latent1[sl], latent2[sl]), axis=0)
        if conditional:
            lab = jnp.concatenate((labels[sl], labels[sl]), axis=0)
            outputs = generator(z, lab)
        else:
            outputs = generator(z)
        out1, out2 = jnp.split(jnp.asarray(outputs), 2, axis=0)
        # [0, 255] -> [-1, 1], the similarity nets' expected domain
        sim = sim_net(2 * (out1 / 255) - 1, 2 * (out2 / 255) - 1)
        distances.append(jnp.asarray(sim).reshape(-1) / epsilon**2)
    dist = jnp.concatenate(distances)

    lower = jnp.quantile(dist, lower_discard, method="lower") if lower_discard is not None else jnp.asarray(0.0)
    upper = jnp.quantile(dist, upper_discard, method="lower") if upper_discard is not None else jnp.max(dist)
    kept = dist[np.asarray((dist >= lower) & (dist <= upper))]
    return jnp.mean(kept), jnp.std(kept, ddof=1), kept


class PerceptualPathLength(Metric):
    """PPL module form (reference ``image/perceptual_path_length.py:32``): compute-only metric.

    Example:
        >>> from torchmetrics_tpu.image import PerceptualPathLength
        >>> metric = PerceptualPathLength(num_samples=8)  # doctest: +SKIP
        >>> metric.update(generator)  # the generator is supplied via update  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
    """

    higher_is_better = False
    is_differentiable = False
    full_state_update = True  # forward() must run the overridden update() (generator capture)
    jit_update = False
    jit_compute = False

    def __init__(
        self,
        num_samples: int = 10_000,
        conditional: bool = False,
        batch_size: int = 64,
        interpolation_method: str = "lerp",
        epsilon: float = 1e-4,
        lower_discard: Optional[float] = 0.01,
        upper_discard: Optional[float] = 0.99,
        sim_net: Optional[Callable[[Array, Array], Array]] = None,
        seed: int = 0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_samples = num_samples
        self.conditional = conditional
        self.batch_size = batch_size
        self.interpolation_method = interpolation_method
        self.epsilon = epsilon
        self.lower_discard = lower_discard
        self.upper_discard = upper_discard
        self.sim_net = sim_net
        self.seed = seed
        self.add_state("_dummy", jnp.zeros(()), dist_reduce_fx="sum")
        self._generator: Any = None

    def _update(self, state: Dict[str, Array], generator: Any = None) -> Dict[str, Array]:
        return {}

    def update(self, generator: Any) -> None:  # noqa: D102
        self._generator = generator
        self._update_count += 1
        self._update_called = True
        self._computed = None

    def _compute(self, state: Dict[str, Any]):
        return perceptual_path_length(
            self._generator,
            num_samples=self.num_samples,
            conditional=self.conditional,
            batch_size=self.batch_size,
            interpolation_method=self.interpolation_method,
            epsilon=self.epsilon,
            lower_discard=self.lower_discard,
            upper_discard=self.upper_discard,
            sim_net=self.sim_net,
            seed=self.seed,
        )
