"""CLIP multimodal module metrics (reference ``src/torchmetrics/multimodal/{clip_score,clip_iqa}.py``)."""
from __future__ import annotations

from typing import Any, Dict, List, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.multimodal.clip import (
    EncoderPair,
    _clip_iqa_compute,
    _clip_iqa_format_prompts,
    _clip_score_update,
    _normalize,
    _resolve_encoders,
)
from torchmetrics_tpu.metric import Metric


class CLIPScore(Metric):
    """CLIPScore (reference ``multimodal/clip_score.py:43``): streaming sum + count states.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.multimodal import CLIPScore
        >>> metric = CLIPScore()  # needs a cached HF CLIP checkpoint  # doctest: +SKIP
        >>> images = [np.random.randint(0, 255, (3, 224, 224)).astype(np.uint8)]
        >>> metric.update(images, ['a photo of a cat'])  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True  # forward() must route through the encoder-running update()
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0
    jit_update = False

    def __init__(
        self,
        model_name_or_path: Union[str, EncoderPair] = "openai/clip-vit-large-patch14",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.image_encoder, self.text_encoder = _resolve_encoders(model_name_or_path)
        self.add_state("score", jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")
        self.add_state("n_samples", jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")  # jaxlint: disable=TPU005 — int32 is the TPU-native count dtype (x64 off; int64 would lower to int32), and sample-scale counts stay far below 2^31

    def update(self, images, text) -> None:  # noqa: D102 - runs the encoders, then delegates
        score, n = _clip_score_update(images, text, self.image_encoder, self.text_encoder)
        super().update(jnp.sum(score), n)

    def _update(self, state: Dict[str, Array], score_sum: Array, n: Array) -> Dict[str, Array]:
        return {"score": state["score"] + score_sum, "n_samples": state["n_samples"] + n}

    def _compute(self, state: Dict[str, Any]) -> Array:
        return jnp.maximum(state["score"] / state["n_samples"], 0.0)


class CLIPImageQualityAssessment(Metric):
    """CLIP-IQA (reference ``multimodal/clip_iqa.py:56``): cat-state of per-image prompt probs.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.multimodal import CLIPImageQualityAssessment
        >>> metric = CLIPImageQualityAssessment(  # needs a cached HF CLIP checkpoint
        ...     model_name_or_path='openai/clip-vit-base-patch16')  # doctest: +SKIP
        >>> metric.update(np.random.rand(1, 3, 224, 224).astype(np.float32))  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    jit_update = False
    jit_compute = False

    def __init__(
        self,
        model_name_or_path: Union[str, EncoderPair] = "clip_iqa",
        data_range: float = 1.0,
        prompts: Tuple[Union[str, Tuple[str, str]], ...] = ("quality",),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(data_range, (int, float)) and data_range > 0):
            raise ValueError('Argument `data_range` must be a positive number.')
        self.data_range = data_range
        self.prompts_names, self.prompts_list = _clip_iqa_format_prompts(prompts)
        if isinstance(model_name_or_path, str) and model_name_or_path == "clip_iqa":
            raise ModuleNotFoundError(
                "The 'clip_iqa' checkpoint (piq) is not bundled in this build; pass"
                " `model_name_or_path` as (image_encoder, text_encoder) callables or a cached"
                " HuggingFace CLIP id."
            )
        self.image_encoder, self.text_encoder = _resolve_encoders(model_name_or_path, rescale_uint8=False)
        self._anchors = None
        self.add_state("probs_list", [], dist_reduce_fx="cat")

    def _anchor_vectors(self) -> Array:
        if self._anchors is None:
            self._anchors = _normalize(self.text_encoder(self.prompts_list))
        return self._anchors

    def update(self, images) -> None:  # noqa: D102 - runs the encoders, then delegates
        images = jnp.asarray(images, jnp.float32)
        if images.ndim != 4:
            raise ValueError(f"Expected `images` to be a batched 4d tensor (N, C, H, W), got shape {images.shape}")
        images = images / float(self.data_range)
        img_features = _normalize(self.image_encoder(images))
        probs = _clip_iqa_compute(img_features, self._anchor_vectors(), self.prompts_names, format_as_dict=False)
        super().update(jnp.atleast_2d(probs.reshape(images.shape[0], -1)))

    def _update(self, state: Dict[str, Array], probs: Array) -> Dict[str, Array]:
        return {"probs_list": probs}

    def _compute(self, state: Dict[str, Any]):
        probs = state["probs_list"]
        if isinstance(probs, list):
            raise RuntimeError("No images accumulated; call `update` before `compute`.")
        if len(self.prompts_names) == 1:
            return jnp.squeeze(probs)
        return {p: probs[:, i] for i, p in enumerate(self.prompts_names)}
