"""Multimodal module metrics (reference ``src/torchmetrics/multimodal/``)."""
from torchmetrics_tpu.multimodal.clip import CLIPImageQualityAssessment, CLIPScore

__all__ = ["CLIPImageQualityAssessment", "CLIPScore"]
