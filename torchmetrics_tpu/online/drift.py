"""Drift detection: windowed metric state vs a reference, alarmed through the SLO stack.

A sliding window (:class:`~torchmetrics_tpu.online.windowed.Windowed`) makes the live
distribution of a served stream observable in O(1) state; this module turns that state
into *alarms*. Three detector families, all host-side and O(sketch) — no raw data is
ever retained or compared:

- :class:`KsDrift` — Kolmogorov–Smirnov distance between the current window's KLL
  sketch and a reference (sketch-to-sketch at the merged support: both CDFs are exact
  functions of the two fixed ~KB sketch states).
- :class:`PsiDrift` — Population Stability Index over quantile-grid bins derived from
  the reference (the industry-standard "has the score distribution moved" number;
  rule-of-thumb: 0.1 drifting, 0.25 shifted).
- :class:`EwmaBand` — an EWMA control band over a scalar value stream (the emitted
  window values): score is the deviation in sigma units. State is three floats —
  snapshot/restore-able, so chaos recovery can prove detector state survives
  preemption bit-identically.

A :class:`DriftSpec` names a detector, a score threshold, and a multi-window burn-rate
policy; :class:`DriftMonitor` records each evaluation's score into a ``drift.<name>.
score`` live series and drives the PR-12 :class:`~torchmetrics_tpu.obs.slo.SloMonitor`
over it — so a drift alarm gets exactly the serving-SLO treatment: a one-shot
``rank_zero_warn`` per transition, ``slo.alarms`` / ``drift.alarms`` counters, and a
burn-rate gauge in the OpenMetrics exposition. ``default_drift_specs`` is the one-call
constructor serving users pair with ``obs.default_serve_specs()``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.obs.slo import DEFAULT_WINDOWS, SloMonitor, SloSpec, SloStatus
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError

__all__ = [
    "DriftDetector",
    "DriftMonitor",
    "DriftSpec",
    "EwmaBand",
    "KsDrift",
    "PsiDrift",
    "default_drift_specs",
]

#: PSI rule-of-thumb alarm threshold ("population has shifted")
DEFAULT_PSI_THRESHOLD = 0.25
#: KS-distance default alarm threshold
DEFAULT_KS_THRESHOLD = 0.15


# ---------------------------------------------------------------------------
# weighted-point plumbing (host numpy; sketches expose their support explicitly)
# ---------------------------------------------------------------------------

def _metric_sketch_state(metric: Any, state: str) -> Any:
    """The named sketch state — merged over the ring for Windowed metrics."""
    window_state = getattr(metric, "window_state", None)
    source = window_state() if callable(window_state) else metric.metric_state
    if state not in source:
        raise TorchMetricsUserError(
            f"{type(metric).__name__} has no state {state!r}; registered states are"
            f" {sorted(source)}"
        )
    return source[state]


def _as_points(ref: Any, state: str = "sketch") -> Tuple[np.ndarray, np.ndarray]:
    """Coerce a reference into (values, weights) support points.

    Accepts a raw sample array (unit weights — the exact empirical distribution), a
    2-D KLL sketch state, or a metric holding one (``StreamingQuantile`` or a
    ``Windowed`` wrapper of it).
    """
    from torchmetrics_tpu.sketch.kll import kll_weighted_points

    if hasattr(ref, "_state"):  # a Metric
        ref = _metric_sketch_state(ref, state)
    arr = np.asarray(ref)
    if arr.ndim == 2:  # a KLL state (levels, capacity+2)
        v, w = kll_weighted_points(ref if not isinstance(ref, np.ndarray) else arr)
        return np.asarray(v, np.float64), np.asarray(w, np.float64)
    values = arr.astype(np.float64).reshape(-1)
    return np.sort(values), np.ones(values.size, np.float64)


def _cdf_at(values: np.ndarray, weights: np.ndarray, xs: np.ndarray) -> np.ndarray:
    """Weighted empirical CDF of (values, weights) evaluated at ``xs``."""
    finite = np.isfinite(values) & (weights > 0)
    v, w = values[finite], weights[finite]
    if v.size == 0:
        return np.zeros_like(xs, np.float64)
    order = np.argsort(v, kind="stable")
    v, w = v[order], w[order]
    cw = np.cumsum(w)
    idx = np.searchsorted(v, xs, side="right")
    cdf = np.where(idx > 0, cw[np.clip(idx - 1, 0, len(cw) - 1)], 0.0)
    return cdf / cw[-1]


def ks_distance_points(
    a: Tuple[np.ndarray, np.ndarray], b: Tuple[np.ndarray, np.ndarray]
) -> float:
    """KS distance between two weighted empirical distributions (numpy twin of
    ``sketch.kll.kll_ks_distance``; parity-tested)."""
    support = np.concatenate([a[0], b[0]])
    support = np.sort(support[np.isfinite(support)])
    if support.size == 0:
        return 0.0
    return float(np.max(np.abs(_cdf_at(*a, support) - _cdf_at(*b, support))))


def psi_points(
    ref: Tuple[np.ndarray, np.ndarray],
    cur: Tuple[np.ndarray, np.ndarray],
    bins: int = 10,
) -> float:
    """Population Stability Index over quantile-grid bins derived from the reference
    (numpy twin of ``sketch.kll.kll_psi``; masses are epsilon-clamped so empty bins
    contribute a finite penalty instead of an infinity)."""
    v, w = ref
    finite = np.isfinite(v) & (w > 0)
    v, w = v[finite], w[finite]
    if v.size == 0:
        return 0.0
    order = np.argsort(v, kind="stable")
    v, w = v[order], w[order]
    cw = np.cumsum(w)
    targets = np.linspace(0.0, 1.0, bins + 1)[1:-1] * cw[-1]
    edges = v[np.minimum(np.searchsorted(cw, targets, side="left"), v.size - 1)]
    grid = np.concatenate([[-np.inf], edges, [np.inf]])
    p = np.diff(_cdf_at(*ref, grid[1:-1]), prepend=0.0, append=1.0)
    q = np.diff(_cdf_at(*cur, grid[1:-1]), prepend=0.0, append=1.0)
    eps = 1e-6
    p, q = np.clip(p, eps, None), np.clip(q, eps, None)
    return float(np.sum((q - p) * np.log(q / p)))


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------

class DriftDetector:
    """One drift score source: ``score()`` returns the current drift magnitude, or
    ``None`` when there is no evidence yet (empty window, warmup). Detectors are
    host-side and deterministic — state (if any) is plain floats."""

    def score(self) -> Optional[float]:
        raise NotImplementedError

    def state(self) -> Dict[str, float]:
        """Serialisable detector state (empty for stateless detectors)."""
        return {}

    def restore(self, state: Dict[str, float]) -> None:
        """Restore a :meth:`state` payload (no-op for stateless detectors)."""


class KsDrift(DriftDetector):
    """KS distance between ``metric``'s (window-merged) KLL sketch and ``reference``.

    O(1) in the stream: both sides are fixed-size sketch supports. ``reference`` is a
    sample array, a KLL state, or a metric holding one (see ``_as_points``).
    """

    def __init__(self, metric: Any, reference: Any, state: str = "sketch") -> None:
        self.metric = metric
        self.state_name = state
        self._ref = _as_points(reference, state)

    def score(self) -> Optional[float]:
        from torchmetrics_tpu.sketch.kll import kll_count

        sk = _metric_sketch_state(self.metric, self.state_name)
        if float(np.asarray(kll_count(sk))) <= 0:
            return None  # empty window: no evidence either way
        return ks_distance_points(_as_points(sk), self._ref)


class PsiDrift(DriftDetector):
    """PSI between ``metric``'s (window-merged) KLL sketch and ``reference`` over
    ``bins`` reference-quantile bins."""

    def __init__(self, metric: Any, reference: Any, bins: int = 10, state: str = "sketch") -> None:
        if bins < 2:
            raise ValueError(f"PsiDrift needs bins >= 2, got {bins}")
        self.metric = metric
        self.state_name = state
        self.bins = int(bins)
        self._ref = _as_points(reference, state)

    def score(self) -> Optional[float]:
        from torchmetrics_tpu.sketch.kll import kll_count

        sk = _metric_sketch_state(self.metric, self.state_name)
        if float(np.asarray(kll_count(sk))) <= 0:
            return None
        return psi_points(self._ref, _as_points(sk), bins=self.bins)


class EwmaBand(DriftDetector):
    """EWMA control band over a scalar value stream: score = |x − ewma| in sigma units.

    Feed values explicitly with :meth:`observe` (each call scores the value against
    the band BEFORE folding it in, so a genuine level shift cannot mask itself), or
    bind a ``metric`` whose scalar window value is read on every :meth:`score` call.
    Warmup observations return ``None`` (no evidence). State is three floats —
    deterministic and snapshot/restore-able.
    """

    def __init__(
        self,
        metric: Any = None,
        alpha: float = 0.1,
        warmup: int = 5,
        min_sigma: float = 1e-9,
    ) -> None:
        if not (0.0 < alpha <= 1.0):
            raise ValueError(f"EwmaBand needs alpha in (0, 1], got {alpha}")
        self.metric = metric
        self.alpha = float(alpha)
        self.warmup = max(1, int(warmup))
        self.min_sigma = float(min_sigma)
        self._mean = 0.0
        self._var = 0.0
        self._n = 0

    def observe(self, value: float) -> Optional[float]:
        """Score ``value`` against the current band, then fold it into the EWMA."""
        value = float(value)
        if self._n >= self.warmup:
            sigma = max(np.sqrt(self._var), self.min_sigma)
            z = abs(value - self._mean) / sigma
        else:
            z = None
        a = self.alpha
        if self._n == 0:
            self._mean = value
        else:
            delta = value - self._mean
            self._mean += a * delta
            self._var = (1.0 - a) * (self._var + a * delta * delta)
        self._n += 1
        return z

    def score(self) -> Optional[float]:
        if self.metric is None:
            raise TorchMetricsUserError(
                "This EwmaBand has no bound metric: drive it with observe(value), or"
                " construct it with EwmaBand(metric=...)"
            )
        reader = getattr(self.metric, "window_values", None)
        value = reader() if callable(reader) else self.metric.compute()
        arr = np.asarray(value)
        if arr.size != 1:
            raise TorchMetricsUserError(
                f"EwmaBand needs a scalar value stream; {type(self.metric).__name__}"
                f" produced shape {arr.shape}"
            )
        return self.observe(float(arr.reshape(())))

    def state(self) -> Dict[str, float]:
        return {"mean": self._mean, "var": self._var, "n": float(self._n)}

    def restore(self, state: Dict[str, float]) -> None:
        self._mean = float(state["mean"])
        self._var = float(state["var"])
        self._n = int(state["n"])


# ---------------------------------------------------------------------------
# specs + monitor
# ---------------------------------------------------------------------------

@dataclass
class DriftSpec:
    """One drift objective: a detector, a score threshold, and the burn-rate policy.

    ``threshold`` is in the detector's own units (KS distance, PSI nats, EWMA
    sigmas). ``objective``/``windows`` parameterise the SLO burn-rate evaluation over
    the recorded score series — the same multi-window "sustained AND still happening"
    recipe the serving SLOs use, which keeps drift alarms spike-proof.
    """

    name: str
    detector: DriftDetector
    threshold: float
    objective: float = 0.999
    windows: Tuple[Tuple[float, float], ...] = DEFAULT_WINDOWS
    description: str = ""

    def as_slo_spec(self) -> SloSpec:
        return SloSpec(
            name=self.name,
            series=f"drift.{self.name}.score",
            objective=self.objective,
            threshold=self.threshold,
            bad_when="above",
            windows=self.windows,
            description=self.description
            or f"drift score above {self.threshold:g} (docs/online.md)",
        )


@dataclass
class DriftStatus:
    """One drift evaluation: the raw score plus the SLO burn verdict."""

    spec: DriftSpec
    score: Optional[float]
    slo: Optional[SloStatus]

    @property
    def drifting(self) -> bool:
        return bool(self.slo is not None and self.slo.burning)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "name": self.spec.name,
            "score": None if self.score is None else round(self.score, 6),
            "threshold": self.spec.threshold,
            "drifting": self.drifting,
            "slo": None if self.slo is None else self.slo.as_dict(),
        }


class DriftMonitor:
    """Evaluates drift specs through the SLO burn-rate machinery.

    Each :meth:`evaluate` call scores every detector, records the scores into
    ``drift.<name>.score`` live series (+ gauges), and runs the embedded
    :class:`SloMonitor` over them — firing alarms with the full serving-SLO
    treatment (one-shot warn per transition, counters, burn gauges). ``now`` pins
    the clock for tests; production callers leave it None.
    """

    def __init__(self, specs: Sequence[DriftSpec] = (), registry: Any = None) -> None:
        self.specs: List[DriftSpec] = list(specs)
        self._tel = registry if registry is not None else obs.telemetry
        self._slo = SloMonitor([s.as_slo_spec() for s in self.specs], registry=self._tel)
        self._subscribers: List[Any] = []
        self._was_firing: set = set()

    def subscribe(self, fn: Any) -> "DriftMonitor":
        """Register ``fn(status, firing)`` to run on every alarm *transition*.

        Called from inside :meth:`evaluate` with the fresh :class:`DriftStatus` when a
        spec transitions into (``firing=True``) or out of (``firing=False``) the
        drifting state — the seam :class:`~torchmetrics_tpu.serve.control.
        DriftSnapshotter` uses to land a pre-shift snapshot + bundle at the exact
        evaluation that fires. Steady states (still firing / still quiet) do not call.
        """
        self._subscribers.append(fn)
        return self

    def watch(self, spec: DriftSpec) -> "DriftMonitor":
        self.specs.append(spec)
        self._slo.watch(spec.as_slo_spec())
        return self

    def evaluate(self, now: Optional[float] = None) -> List[DriftStatus]:
        scores: Dict[str, Optional[float]] = {}
        for spec in self.specs:
            self._tel.counter("drift.evaluations").inc()
            s = spec.detector.score()
            scores[spec.name] = s
            if s is None:
                continue  # no evidence: the empty window cannot satisfy any burn
            self._tel.series(f"drift.{spec.name}.score").record(float(s), now=now)
            self._tel.gauge(f"drift.{spec.name}.score").set(float(s))
        statuses = {st.spec.name: st for st in self._slo.evaluate(now=now)}
        out: List[DriftStatus] = []
        for spec in self.specs:
            st = statuses.get(spec.name)
            if st is not None and st.burning:
                self._tel.counter("drift.alarms").inc()
                self._tel.counter(f"drift.alarms.{spec.name}").inc()
            out.append(DriftStatus(spec=spec, score=scores[spec.name], slo=st))
        for status in out:
            firing = status.drifting
            was = status.spec.name in self._was_firing
            if firing == was:
                continue  # steady state: subscribers only see transitions
            (self._was_firing.add if firing else self._was_firing.discard)(status.spec.name)
            for fn in self._subscribers:
                fn(status, firing)
        return out

    def drifting(self) -> List[str]:
        """Names of specs whose last evaluation fired."""
        return self._slo.burning()


def default_drift_specs(
    metric: Any,
    reference: Any,
    name: Optional[str] = None,
    ks_threshold: float = DEFAULT_KS_THRESHOLD,
    psi_threshold: float = DEFAULT_PSI_THRESHOLD,
    psi_bins: int = 10,
    windows: Tuple[Tuple[float, float], ...] = DEFAULT_WINDOWS,
) -> List[DriftSpec]:
    """The stock quality alarms for a served, windowed, sketch-backed metric.

    One call gives serving users model-quality drift alarms next to their
    ``obs.default_serve_specs()`` system alarms: a KS-distance spec and a PSI spec,
    both comparing ``metric``'s (window-merged) KLL sketch against ``reference`` —
    a held-out sample array, a reference sketch state, or a warmed-up twin metric.
    """
    base = name or f"{type(metric).__name__.lower()}-drift"
    return [
        DriftSpec(
            name=f"{base}-ks",
            detector=KsDrift(metric, reference),
            threshold=ks_threshold,
            windows=windows,
            description="KS distance of the live window vs the reference distribution",
        ),
        DriftSpec(
            name=f"{base}-psi",
            detector=PsiDrift(metric, reference, bins=psi_bins),
            threshold=psi_threshold,
            windows=windows,
            description="PSI of the live window vs the reference distribution",
        ),
    ]
