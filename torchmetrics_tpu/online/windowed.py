"""Windowed metric state: sliding rings and EMA decay as first-class, fixed-shape states.

A production serving stack needs the metric *values* flowing through it to be visible
continuously, not once per epoch (ROADMAP item 2; *Fine-Tuning and Serving Gemma 4 31B
on Google Cloud TPU*, PAPERS.md). The blockers have always been state shape and host
coordination: a naive sliding window keeps the raw samples (unbounded state — exactly
what PR 10's sketches eliminated), and a host-driven window boundary is both a retrace
hazard and non-reproducible under WAL replay (jaxlint TPU017 exists precisely for the
wall-clock version of this bug).

Design — the KeyedMetric trick, turned 90 degrees (docs/online.md):

- **State**: :class:`Windowed` wraps a template metric and registers every template
  tensor state with a leading ``[window, ...]`` ring axis — ``window`` tumbling
  sub-window slabs, each accumulated with the template's OWN ``_update`` kernel, plus
  three scalar bookkeeping states (``window_slot`` / ``window_count`` /
  ``window_advances``). The whole ring is a fixed-shape pytree of ordinary states, so
  EVERY engine seam — jit, AOT+donation, ``update_scan``, buffered windows, keyed
  templates, ``shard()``, snapshot/journal/quorum sync — applies unchanged.

- **Advance is in-graph and update-count-driven.** With ``advance_every=n`` the update
  kernel itself rotates the ring: after the slot's ``n``-th update it moves the slot
  pointer, resets the next slab to the template defaults (dropping the oldest
  sub-window), and bumps the advance counter — all ``jnp.where`` selects over fixed
  shapes, no host round-trips, no wall clock. Window boundaries are therefore a pure
  function of the update count: a WAL replay (``snapshot + replay(journal)``)
  reconstructs the ring BIT-identically, and the serve drain advances windows simply by
  applying batches (batch-count ticks, quiesce-safe by the single-mutator contract).

- **Compute merges the live sub-windows** through the same reduction ladder the
  engine already trusts: ``sum`` states fold as ``default + Σ(slab - default)``,
  ``max``/``min`` as the axis-0 reduction, and trace-safe callable merges (the KLL
  compactor's ``kll_merge_stacked``) fold the ``[window, ...]`` stack directly — so
  every PR-10 mergeable sketch gets sliding semantics for free. For named reductions
  the window value is bit-identical to a fresh metric fed exactly the window's batches
  (with order-exact inputs, e.g. integer-valued f32); for sketch merges it is
  bit-identical to explicitly merging per-sub-window sketches (the mergeable-sketch
  contract — see docs/online.md).

- **EMA / time-decayed** (:class:`Ema`): the decay is ONE extra fused multiply applied
  to the (sum-reduced) state inside the update kernel — no ring, no host work. Decay is
  per *update*, deliberately not per wall-clock second: deterministic, replayable, and
  trace-stable (TPU017 again).

Per-window observability: each advance bumps ``online.windows_advanced`` and (when
``emit=True``) records the freshly-computed sliding value into the always-on
``online.<Template>.w<window>`` :class:`~torchmetrics_tpu.obs.timeseries.TimeSeries`
(+ a matching OpenMetrics gauge) — one deliberate device read per ``advance_every``
updates, amortized. Drift detection over these windows lives in
:mod:`torchmetrics_tpu.online.drift`.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu import obs
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.ops import dispatch as _dispatch
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError

_SUM_FX = ("sum", jnp.sum)
_MAX_FX = ("max", jnp.max)
_MIN_FX = ("min", jnp.min)

#: bookkeeping states registered alongside the ring slabs (reserved names)
SLOT_STATE = "window_slot"
COUNT_STATE = "window_count"
ADVANCES_STATE = "window_advances"
_BOOKKEEPING = (SLOT_STATE, COUNT_STATE, ADVANCES_STATE)


def _slotwise_merge(fx: Callable) -> Callable:
    """Slot-wise twin of a trace-safe merge callable for ``[window, ...]`` ring states.

    ``process_sync`` stacks per-rank states to ``(world, window, ...)`` while the
    template's merge expects ``(world, ...)``; vmapping over the slot axis merges each
    ring slot across ranks independently. The wrapper stays declared trace-safe so the
    quorum/forward merge ladders keep accepting it.
    """

    def slotwise(stacked: Array) -> Array:
        return jax.vmap(fx, in_axes=1, out_axes=0)(stacked)

    slotwise.traceable = True
    slotwise.__name__ = f"windowed_{getattr(fx, '__name__', 'merge')}"
    return slotwise


def _check_template(metric: Union[Metric, type], kind: str) -> Metric:
    if isinstance(metric, type):
        if not issubclass(metric, Metric):
            raise ValueError(f"Expected a Metric instance or subclass, got {metric!r}")
        metric = metric()
    if not isinstance(metric, Metric):
        raise ValueError(f"Expected a Metric instance or subclass, got {metric!r}")
    if isinstance(metric, (Windowed, Ema)):
        raise ValueError(f"{kind} cannot be nested: pass the plain template metric")
    if metric._state.lists:
        raise TorchMetricsUserError(
            f"{type(metric).__name__} holds list ('cat') states, which have no fixed"
            f" per-window shape — only tensor-state metrics can be {kind.lower()}ed."
            " Bound the state first (e.g. a binned/sketched variant) and window that."
        )
    if not (metric.jit_update and metric.jit_compute):
        raise TorchMetricsUserError(
            f"{type(metric).__name__} opts out of jit (jit_update/jit_compute=False):"
            f" its kernels cannot trace into the fused {kind.lower()}ed program."
        )
    for name in metric._state.tensors:
        if name in _BOOKKEEPING:
            raise TorchMetricsUserError(
                f"{type(metric).__name__} registers a state named {name!r}, which is"
                f" reserved for {kind}'s ring bookkeeping."
            )
    return metric


class Windowed(Metric):
    """Sliding-window view of a template metric: a ring of tumbling sub-window slabs.

    ``window`` is the number of sub-windows in the ring; ``advance_every`` (updates per
    sub-window) drives the in-graph rotation — after every ``advance_every``-th update
    the slot pointer moves on and the slab it moves into is reset to the template
    defaults, so :meth:`compute` always covers the last ``window`` sub-windows
    (including the live, partially-filled one). With ``advance_every=None`` the ring
    only rotates on explicit :meth:`advance` calls (manual tumbling — note that manual
    advances are NOT write-ahead journaled; use ``advance_every`` wherever replay
    fidelity matters, e.g. under ``serve(journal=...)``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> from torchmetrics_tpu.online import Windowed
        >>> w = Windowed(SumMetric(), window=2, advance_every=2, emit=False)
        >>> for v in (1.0, 2.0, 4.0, 8.0, 16.0):
        ...     w.update(np.asarray([v], np.float32))
        >>> float(w.compute())  # last 2 sub-windows: (4+8) + 16
        28.0
        >>> w.windows_advanced
        2
    """

    #: update-only protocol: opt into the AOT+donation plain-update tier
    fast_update = True
    #: the ring fold does not decompose under segment reductions
    keyed_decomposable = False

    def __init__(
        self,
        metric: Union[Metric, type],
        window: int,
        advance_every: Optional[int] = None,
        emit: bool = True,
        series: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        metric = _check_template(metric, "Windowed")
        window = int(window)
        if window < 1:
            raise ValueError(f"Windowed needs window >= 1, got {window}")
        if advance_every is not None:
            advance_every = int(advance_every)
            if advance_every < 1:
                raise ValueError(f"Windowed needs advance_every >= 1, got {advance_every}")
        self._template = metric
        self.window = window
        self.advance_every = advance_every
        self._tpl_names = tuple(metric._state.tensors)
        self._emit = bool(emit)
        self._series_name = series or f"online.{type(metric).__name__}.w{window}"
        for name in self._tpl_names:
            fx = metric._reductions[name]
            if fx in _SUM_FX or fx in _MAX_FX or fx in _MIN_FX:
                ring_fx: Any = fx
            elif callable(fx) and getattr(fx, "traceable", False):
                ring_fx = _slotwise_merge(fx)
            else:
                raise TorchMetricsUserError(
                    f"{type(metric).__name__} state {name!r} has dist_reduce_fx={fx!r},"
                    " which the window merge ladder cannot fold — windowed states need"
                    " sum/max/min or a trace-safe callable merge (sketch states)."
                )
            default = metric._defaults[name]
            ring_default = jnp.broadcast_to(default, (window,) + tuple(jnp.shape(default)))
            self.add_state(name, ring_default, dist_reduce_fx=ring_fx)
        # bookkeeping rides the ordinary state machinery (donated/scanned/journaled/
        # snapshotted); all ranks advance in lockstep, so "max" is the identity sync
        self.add_state(SLOT_STATE, jnp.asarray(0, jnp.int32), dist_reduce_fx="max")
        self.add_state(COUNT_STATE, jnp.asarray(0, jnp.int32), dist_reduce_fx="max")
        self.add_state(ADVANCES_STATE, jnp.asarray(0, jnp.int32), dist_reduce_fx="max")
        self._advances_seen = 0

    # ------------------------------------------------------------------ properties
    @property
    def template(self) -> Metric:
        """The template metric the per-slot kernels come from (never updated itself)."""
        return self._template

    @property
    def windows_advanced(self) -> int:
        """Total ring advances so far (host-tracked; no device read)."""
        return self._advances_seen

    @property
    def series_name(self) -> str:
        """The ``online.*`` live-series name advance emissions record into."""
        return self._series_name

    @property
    def online_descriptor(self) -> Dict[str, Any]:
        """Snapshot-blob ``window`` descriptor (validated on restore BEFORE shapes —
        two rings of different geometry or advance cadence are not the same state even
        when their arrays happen to agree in shape)."""
        return {
            "mode": "sliding",
            "window": int(self.window),
            "advance_every": None if self.advance_every is None else int(self.advance_every),
            "template": type(self._template).__name__,
        }

    # ------------------------------------------------------------------ kernels
    def _ring_row_default(self, name: str) -> Array:
        return self._template._defaults[name]

    def _update(self, state: Dict[str, Array], *args: Any, **kwargs: Any) -> Dict[str, Array]:
        tpl = self._template
        slot = state[SLOT_STATE]
        row_state = {
            n: jax.lax.dynamic_index_in_dim(state[n], slot, axis=0, keepdims=False)
            for n in self._tpl_names
        }
        out = tpl._update(dict(row_state), *args, **kwargs)
        new: Dict[str, Array] = {}
        for n in self._tpl_names:
            row = out.get(n, row_state[n])
            new[n] = jax.lax.dynamic_update_index_in_dim(state[n], row, slot, axis=0)
        count = state[COUNT_STATE] + 1
        advances = state[ADVANCES_STATE]
        if self.advance_every is not None:
            # eager in-graph advance: the moment a sub-window fills, rotate the pointer
            # and reset the slab it rotates into (dropping the oldest sub-window), so a
            # compute() between updates never sees a stale (window+1)-th sub-window
            do_adv = count >= self.advance_every
            nxt = jnp.mod(slot + 1, self.window)
            for n in self._tpl_names:
                cleared = jax.lax.dynamic_update_index_in_dim(
                    new[n], self._ring_row_default(n), nxt, axis=0
                )
                new[n] = jnp.where(do_adv, cleared, new[n])
            slot = jnp.where(do_adv, nxt, slot)
            count = jnp.where(do_adv, 0, count)
            advances = advances + do_adv.astype(advances.dtype)
        new[SLOT_STATE] = slot
        new[COUNT_STATE] = count
        new[ADVANCES_STATE] = advances
        return new

    def _merge_ring(self, state: Dict[str, Array]) -> Dict[str, Array]:
        """Fold the ``[window, ...]`` slabs into one template state — the same reduction
        ladder the engine's forward merge and ``process_sync`` use, so empty slabs are
        exact identities (zero sum contribution, ±inf extrema, the empty sketch)."""
        tpl = self._template
        merged: Dict[str, Array] = {}
        for n in self._tpl_names:
            fx = tpl._reductions[n]
            v = state[n]
            if fx in _SUM_FX:
                d = tpl._defaults[n]
                merged[n] = d + jnp.sum(v - d, axis=0)
            elif fx in _MAX_FX:
                merged[n] = jnp.max(v, axis=0)
            elif fx in _MIN_FX:
                merged[n] = jnp.min(v, axis=0)
            else:  # trace-safe callable: the ring IS the stacked-merge operand
                merged[n] = fx(v)
        return merged

    def _compute(self, state: Dict[str, Any]) -> Any:
        return self._template._compute(self._merge_ring(state))

    # ------------------------------------------------------------------ protocol
    def update(self, *args: Any, **kwargs: Any) -> None:
        """Fold one batch into the live sub-window (rotating in-graph when it fills)."""
        super().update(*args, **kwargs)
        self._online_tick()

    def update_batches(self, *args: Any, **kwargs: Any) -> None:
        """Whole-stack sweep; ring rotations buried inside the scan are counted (and the
        latest window value emitted once) on return."""
        super().update_batches(*args, **kwargs)
        self._online_tick()

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        raise TorchMetricsUserError(
            "Windowed has no per-batch forward value: the window merge is not a batch"
            " reduction. Drive it with update(...) and read the sliding value with"
            " compute() (or the online.* live series the advances emit)."
        )

    def advance(self) -> None:
        """Manually close the live sub-window (only with ``advance_every=None``).

        Rotates the ring in one compiled launch: pointer forward, the slab it moves
        into reset to defaults. Manual advances are host-driven and NOT journaled —
        a WAL replay cannot reproduce them; use ``advance_every`` for replay fidelity.
        """
        if self.advance_every is not None:
            raise TorchMetricsUserError(
                f"This Windowed metric auto-advances every {self.advance_every}"
                " update(s); mixing manual advance() calls in would make the window"
                " boundaries irreproducible under journal replay."
            )
        _dispatch.guard_buffered_pending(self, "advance")
        if self._serve is not None:
            self._serve.quiesce()
        self._state.guard_readable()
        fn = self._jit_cache.get("window_advance")
        if fn is None:
            names = self._tpl_names

            def advance_kernel(state: Dict[str, Array]) -> Dict[str, Array]:
                nxt = jnp.mod(state[SLOT_STATE] + 1, self.window)
                new = dict(state)
                for n in names:
                    new[n] = jax.lax.dynamic_update_index_in_dim(
                        state[n], self._ring_row_default(n), nxt, axis=0
                    )
                new[SLOT_STATE] = nxt
                new[COUNT_STATE] = jnp.zeros_like(state[COUNT_STATE])
                new[ADVANCES_STATE] = state[ADVANCES_STATE] + 1
                return new

            fn = jax.jit(obs.instrument_trace(advance_kernel, self, "window_advance"))
            self._jit_cache["window_advance"] = fn
        obs.count_dispatch(self)
        out = fn(dict(self._state.tensors))
        for name in self._state.tensors:
            self._state.tensors[name] = out[name]
        self._computed = None
        self._advances_seen += 1
        obs.telemetry.counter("online.windows_advanced").inc()
        if self._emit:
            self._emit_window_value()

    # ------------------------------------------------------------- observability
    def _online_tick(self) -> None:
        """Host tail of every update: count in-graph advances (pure update-count math,
        no device read) and emit the sliding value once per batch of new advances."""
        if self.advance_every is None:
            return
        total = self._update_count // self.advance_every
        new = total - self._advances_seen
        if new <= 0:
            return
        self._advances_seen = total
        obs.telemetry.counter("online.windows_advanced").inc(new)
        if self._emit:
            self._emit_window_value()

    def _emit_window_value(self) -> None:
        """One deliberate device read per advance (amortized over ``advance_every``
        updates): the freshly-closed window's sliding value lands in the always-on
        ``online.*`` series + gauge so dashboards see metric VALUES, not just queues."""
        value = self._jitted_compute()(dict(self._state.tensors))
        arr = np.asarray(value)
        if arr.size != 1:
            # no single dashboard number (e.g. a keyed template's per-key vector);
            # the advance counter still fired — consumers read window_values()
            obs.telemetry.counter("online.emit_skipped").inc()
            return
        v = float(arr.reshape(()))
        obs.telemetry.series(self._series_name).record(v)
        obs.telemetry.gauge(self._series_name).set(v)
        obs.telemetry.counter("online.emitted").inc()

    def window_state(self) -> Dict[str, Array]:
        """Merged template state over the live ring (one fused launch; drift detectors
        read sketch states from here — sketch-to-sketch comparison, no raw data)."""
        _dispatch.guard_buffered_pending(self, "window_state")
        if self._serve is not None:
            self._serve.quiesce()
        self._state.guard_readable()
        fn = self._jit_cache.get("window_merge")
        if fn is None:
            fn = jax.jit(obs.instrument_trace(self._merge_ring, self, "window_merge"))
            self._jit_cache["window_merge"] = fn
        return dict(fn(dict(self._state.tensors)))

    def window_values(self) -> Any:
        """The sliding window's computed value (quiesce-safe, no sync machinery —
        exactly what advance emission records)."""
        _dispatch.guard_buffered_pending(self, "window_values")
        if self._serve is not None:
            self._serve.quiesce()
        self._state.guard_readable()
        return self._jitted_compute()(dict(self._state.tensors))

    # ------------------------------------------------------------------ lifecycle
    def reset(self) -> None:
        super().reset()
        self._advances_seen = 0

    def restore(self, blob: Dict[str, Any]) -> None:
        super().restore(blob)
        # resync the host-side advance counter with the restored ring (the in-graph
        # counter is the truth; emission must not replay a burst after restore)
        self._advances_seen = int(np.asarray(self._state.tensors[ADVANCES_STATE]))

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}({type(self._template).__name__}(),"
            f" window={self.window}, advance_every={self.advance_every})"
        )


class Ema(Metric):
    """Exponentially-decayed view of a template metric: one fused multiply per update.

    Every template state must be sum-reduced (``SumMetric``, ``MeanMetric``'s
    value/weight pair, the curve family's histogram pairs, count-min): the update
    kernel decays the state by ``decay`` before folding the batch in, so after ``t``
    updates each batch ``i`` contributes with weight ``decay^(t-i)`` — the classic
    exponentially-weighted accumulator, in-graph, zero host work. For a ``MeanMetric``
    template (both states decayed identically) ``compute`` is therefore the
    exponentially-weighted mean.

    Decay is per UPDATE, deliberately not per wall-clock second: window semantics stay
    deterministic, journal-replayable, and trace-stable (jaxlint TPU017 flags the
    wall-clock alternative). ``emit_every=n`` records the decayed value into the
    ``online.<Template>.ema`` live series every ``n`` updates.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> from torchmetrics_tpu.online import Ema
        >>> m = Ema(SumMetric(), decay=0.5)
        >>> for v in (1.0, 1.0, 1.0):
        ...     m.update(np.asarray([v], np.float32))
        >>> float(m.compute())  # 0.25 + 0.5 + 1
        1.75
    """

    fast_update = True
    keyed_decomposable = False

    def __init__(
        self,
        metric: Union[Metric, type],
        decay: float = 0.99,
        emit_every: Optional[int] = None,
        series: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        metric = _check_template(metric, "Ema")
        decay = float(decay)
        if not (0.0 < decay <= 1.0):
            raise ValueError(f"Ema needs decay in (0, 1], got {decay}")
        if emit_every is not None:
            emit_every = int(emit_every)
            if emit_every < 1:
                raise ValueError(f"Ema needs emit_every >= 1, got {emit_every}")
        for name, fx in metric._reductions.items():
            if fx not in _SUM_FX:
                raise TorchMetricsUserError(
                    f"{type(metric).__name__} state {name!r} has dist_reduce_fx={fx!r};"
                    " EMA decay is only well-defined for sum-reduced states (decaying"
                    " an extremum or a sketch has no exponential-weighting meaning)."
                    " Use Windowed for bounded-horizon semantics instead."
                )
        self._template = metric
        self.decay = decay
        self.emit_every = emit_every
        self._tpl_names = tuple(metric._state.tensors)
        self._series_name = series or f"online.{type(metric).__name__}.ema"
        self._emitted_at = 0
        for name in self._tpl_names:
            self.add_state(
                name, metric._defaults[name], dist_reduce_fx=metric._reductions[name]
            )

    @property
    def template(self) -> Metric:
        return self._template

    @property
    def series_name(self) -> str:
        return self._series_name

    @property
    def online_descriptor(self) -> Dict[str, Any]:
        """Snapshot-blob ``window`` descriptor (decay cadence is part of the state's
        meaning: restoring a 0.9-decay blob into a 0.99-decay metric is wrong even
        though every array shape matches)."""
        return {
            "mode": "ema",
            "decay": float(self.decay),
            "template": type(self._template).__name__,
        }

    def _update(self, state: Dict[str, Array], *args: Any, **kwargs: Any) -> Dict[str, Array]:
        tpl = self._template
        decayed = {}
        for n in self._tpl_names:
            d = tpl._defaults[n]
            # sum states: default + decay·contribution (defaults are typically zero,
            # but keeping the affine form exact covers custom non-zero sum defaults)
            decayed[n] = d + self.decay * (state[n] - d)
        out = tpl._update(decayed, *args, **kwargs)
        return {n: out.get(n, decayed[n]) for n in self._tpl_names}

    def _compute(self, state: Dict[str, Any]) -> Any:
        return self._template._compute({n: state[n] for n in self._tpl_names})

    def update(self, *args: Any, **kwargs: Any) -> None:
        super().update(*args, **kwargs)
        self._online_tick()

    def update_batches(self, *args: Any, **kwargs: Any) -> None:
        super().update_batches(*args, **kwargs)
        self._online_tick()

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        raise TorchMetricsUserError(
            "Ema has no per-batch forward value: the decayed merge is not the engine's"
            " batch reduction. Drive it with update(...) and read compute()."
        )

    def _online_tick(self) -> None:
        if self.emit_every is None:
            return
        due = self._update_count // self.emit_every
        if due <= self._emitted_at:
            return
        self._emitted_at = due
        value = self._jitted_compute()(dict(self._state.tensors))
        arr = np.asarray(value)
        if arr.size != 1:
            obs.telemetry.counter("online.emit_skipped").inc()
            return
        v = float(arr.reshape(()))
        obs.telemetry.series(self._series_name).record(v)
        obs.telemetry.gauge(self._series_name).set(v)
        obs.telemetry.counter("online.emitted").inc()

    def reset(self) -> None:
        super().reset()
        self._emitted_at = 0

    def __repr__(self) -> str:
        return f"{type(self).__name__}({type(self._template).__name__}(), decay={self.decay})"
