"""torchmetrics_tpu.online — windowed monitoring and drift alarms on the serving path.

Sliding/EMA windows as first-class fixed-shape metric states (``Windowed`` / ``Ema``,
or the ``Metric.windowed()`` / ``Metric.ema()`` / ``MetricCollection.windowed()``
seams), per-window value emission into the always-on ``online.*`` live series, and
drift detection (KS / PSI sketch-to-sketch, EWMA control bands) alarmed through the
SLO burn-rate machinery. See ``docs/online.md``.
"""
from torchmetrics_tpu.online.drift import (
    DriftDetector,
    DriftMonitor,
    DriftSpec,
    EwmaBand,
    KsDrift,
    PsiDrift,
    default_drift_specs,
)
from torchmetrics_tpu.online.windowed import Ema, Windowed

__all__ = [
    "DriftDetector",
    "DriftMonitor",
    "DriftSpec",
    "Ema",
    "EwmaBand",
    "KsDrift",
    "PsiDrift",
    "Windowed",
    "default_drift_specs",
]
