__version__ = "0.1.0"
__author__ = "torchmetrics_tpu contributors"
__license__ = "Apache-2.0"
