"""Aggregation metrics: Max/Min/Sum/Mean/Cat (+ Running variants in ``wrappers.running``).

Parity: reference ``src/torchmetrics/aggregation.py`` (``BaseAggregator:30``, ``MaxMetric:114``,
``MinMetric:219``, ``SumMetric:324``, ``CatMetric:429``, ``MeanMetric:493``, ``RunningMean:616``,
``RunningSum:673``).

TPU-first: the reference's ``'ignore'`` NaN strategy drops elements (``aggregation.py:75-104``) —
a dynamic-shape op. Here NaN handling is mask-and-weight inside the jitted kernel (ignored values
contribute identity elements: 0 to sums, ±inf to min/max), which XLA fuses into the reduction.
``'error'``/``'warn'`` are host-side checks that no-op under trace.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.checks import is_traced
from torchmetrics_tpu.utils.compute import _safe_divide
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.prints import rank_zero_warn
from torchmetrics_tpu.wrappers.running import Running as _Running


class BaseAggregator(Metric):
    """Base class for aggregation metrics (reference ``aggregation.py:30``)."""

    is_differentiable = None
    higher_is_better = None
    full_state_update: bool = False

    def __init__(
        self,
        fn: Union[Callable, str, None],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        state_name: str = "value",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, float):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.add_state(state_name, default=default_value, dist_reduce_fx=fn)
        self.state_name = state_name

    def _should_validate(self) -> bool:
        return self.nan_strategy in ("error", "warn")

    def _validate(self, *args: Any, **kwargs: Any) -> None:
        if not self._should_validate():
            return
        for x in list(args) + list(kwargs.values()):
            if x is None or is_traced(x):
                continue
            if np.isnan(np.asarray(x, dtype=np.float32)).any():
                if self.nan_strategy == "error":
                    raise RuntimeError("Encountered `nan` values in tensor")
                rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)

    def _nan_mask_and_fill(self, x: Array, fill: float) -> Array:
        """Replace NaNs by ``fill`` ('ignore'/'warn' → identity element, float strategy → impute)."""
        x = jnp.asarray(x, jnp.float32)
        if isinstance(self.nan_strategy, float):
            return jnp.nan_to_num(x, nan=self.nan_strategy)
        return jnp.nan_to_num(x, nan=fill)

    def _compute(self, state: Dict[str, Any]) -> Array:
        return state[self.state_name]

    def compute(self) -> Array:
        return super().compute()


class MaxMetric(BaseAggregator):
    """Running maximum of a stream of values (reference ``aggregation.py:114``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import MaxMetric
        >>> metric = MaxMetric()
        >>> metric.update(1.0)
        >>> metric.update(np.array([2.0, 0.5]))
        >>> float(metric.compute())
        2.0
    """

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.asarray(-jnp.inf, jnp.float32), nan_strategy, state_name="max_value", **kwargs)

    def _update(self, state: Dict[str, Array], value: Array) -> Dict[str, Array]:
        if value.size == 0:  # empty update is a no-op (shape is static, safe under trace)
            return {"max_value": state["max_value"]}
        v = self._nan_mask_and_fill(value, -jnp.inf)
        return {"max_value": jnp.maximum(state["max_value"], jnp.max(v))}


class MinMetric(BaseAggregator):
    """Running minimum of a stream of values (reference ``aggregation.py:219``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import MinMetric
        >>> metric = MinMetric()
        >>> metric.update(1.0)
        >>> metric.update(np.array([2.0, 0.5]))
        >>> float(metric.compute())
        0.5
    """

    full_state_update = True

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf, jnp.float32), nan_strategy, state_name="min_value", **kwargs)

    def _update(self, state: Dict[str, Array], value: Array) -> Dict[str, Array]:
        if value.size == 0:  # empty update is a no-op
            return {"min_value": state["min_value"]}
        v = self._nan_mask_and_fill(value, jnp.inf)
        return {"min_value": jnp.minimum(state["min_value"], jnp.min(v))}


class SumMetric(BaseAggregator):
    """Running sum of a stream of values (reference ``aggregation.py:324``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> metric = SumMetric()
        >>> metric.update(1.0)
        >>> metric.update(np.array([2.0, 3.0]))
        >>> float(metric.compute())
        6.0
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0, jnp.float32), nan_strategy, state_name="sum_value", **kwargs)

    def _update(self, state: Dict[str, Array], value: Array) -> Dict[str, Array]:
        v = self._nan_mask_and_fill(value, 0.0)
        return {"sum_value": state["sum_value"] + jnp.sum(v)}


class CatMetric(BaseAggregator):
    """Concatenate a stream of values (reference ``aggregation.py:429``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import CatMetric
        >>> metric = CatMetric()
        >>> metric.update(1.0)
        >>> metric.update(np.array([2.0, 3.0]))
        >>> np.asarray(metric.compute()).tolist()
        [1.0, 2.0, 3.0]
    """

    # NaN filtering changes the output shape, so the update must stay on the host
    jit_update = False

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, state_name="value", **kwargs)

    def _update(self, state: Dict[str, Array], value: Array) -> Dict[str, Array]:
        v = self._nan_mask_and_fill(value, jnp.nan)
        if self.nan_strategy in ("ignore", "warn"):
            # dynamic filter — host-side only (list states are host-mediated anyway)
            if not is_traced(v):
                vn = np.asarray(v, np.float32).reshape(-1)
                v = jnp.asarray(vn[~np.isnan(vn)])
        return {"value": jnp.atleast_1d(v)}

    def _compute(self, state: Dict[str, Any]) -> Array:
        val = state["value"]
        if isinstance(val, list):
            return dim_zero_cat(val) if val else jnp.zeros((0,))
        return val


class MeanMetric(BaseAggregator):
    """Weighted running mean of a stream of values (reference ``aggregation.py:493``).

    ``empty_result`` defines ``compute()`` on zero observations (an untouched metric, or
    one whose every input was NaN-masked away): the division routes through
    ``_safe_divide`` so a zero total weight yields ``empty_result`` exactly — ``0.0`` by
    default, or ``float("nan")`` for reference-torchmetrics semantics — instead of an
    epsilon-clamped quotient.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import MeanMetric
        >>> metric = MeanMetric()
        >>> metric.update(1.0)
        >>> metric.update(np.array([2.0, 3.0]))
        >>> float(metric.compute())
        2.0
        >>> float(MeanMetric().compute())  # zero observations: well-defined, not NaN
        0.0
    """

    def __init__(
        self,
        nan_strategy: Union[str, float] = "warn",
        empty_result: float = 0.0,
        **kwargs: Any,
    ) -> None:
        super().__init__("sum", jnp.asarray(0.0, jnp.float32), nan_strategy, state_name="mean_value", **kwargs)
        if not isinstance(empty_result, (int, float)):
            raise ValueError(f"Arg `empty_result` should be a float (0.0 or nan), but got {empty_result!r}")
        self.empty_result = float(empty_result)
        self.add_state("weight", default=jnp.asarray(0.0, jnp.float32), dist_reduce_fx="sum")

    def _update(self, state: Dict[str, Array], value: Array, weight: Optional[Array] = None) -> Dict[str, Array]:
        value = jnp.asarray(value, jnp.float32)
        if weight is None:
            weight = jnp.ones_like(value)
        weight = jnp.broadcast_to(jnp.asarray(weight, jnp.float32), value.shape)
        nan_mask = jnp.isnan(value) | jnp.isnan(weight)
        if isinstance(self.nan_strategy, float):
            value = jnp.where(nan_mask, self.nan_strategy, value)
            weight = jnp.where(nan_mask, self.nan_strategy, weight)
        else:  # ignore/warn: zero weight for nan entries
            value = jnp.where(nan_mask, 0.0, value)
            weight = jnp.where(nan_mask, 0.0, weight)
        return {
            "mean_value": state["mean_value"] + jnp.sum(value * weight),
            "weight": state["weight"] + jnp.sum(weight),
        }

    def _compute(self, state: Dict[str, Any]) -> Array:
        # _safe_divide, not an epsilon clamp: weight == 0 (zero observations) returns
        # `empty_result` exactly, and tiny-but-real weights divide undistorted
        return _safe_divide(state["mean_value"], state["weight"], zero_division=self.empty_result)


class RunningMean(_Running):
    """Mean over a running window (reference ``aggregation.py:616``).

    Example:
        >>> from torchmetrics_tpu.aggregation import RunningMean
        >>> metric = RunningMean(window=2)
        >>> for v in (1.0, 2.0, 5.0):
        ...     metric.update(v)
        >>> float(metric.compute())  # mean of the last 2 values
        3.5
    """

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(base_metric=MeanMetric(nan_strategy=nan_strategy, **kwargs), window=window)


class RunningSum(_Running):
    """Sum over a running window (reference ``aggregation.py:673``).

    Example:
        >>> from torchmetrics_tpu.aggregation import RunningSum
        >>> metric = RunningSum(window=2)
        >>> for v in (1.0, 2.0, 5.0):
        ...     metric.update(v)
        >>> float(metric.compute())  # sum of the last 2 values
        7.0
    """

    def __init__(self, window: int = 5, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__(base_metric=SumMetric(nan_strategy=nan_strategy, **kwargs), window=window)
