"""PanopticQuality module metrics (reference ``src/torchmetrics/detection/panoptic_qualities.py``)."""
from __future__ import annotations

from typing import Any, Collection, Dict

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.functional.detection.panoptic import (
    _get_category_id_to_continuous_id,
    _get_void_color,
    _panoptic_quality_compute,
    _panoptic_quality_update,
    _parse_categories,
    _preprocess_inputs,
    _validate_inputs,
)
from torchmetrics_tpu.metric import Metric


class PanopticQuality(Metric):
    """PQ over (category, instance) maps (reference ``panoptic_qualities.py:36``).

    Per-category IoU-sum/TP/FP/FN accumulators, all ``dist_reduce_fx="sum"`` — directly
    ``psum``-able; segment matching runs on the host (see ``functional/detection/panoptic.py``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.detection import PanopticQuality
        >>> preds = np.array([[[6, 0], [0, 0], [6, 0], [7, 0]]])
        >>> target = np.array([[[6, 0], [0, 1], [6, 0], [7, 0]]])
        >>> metric = PanopticQuality(things={6, 7}, stuffs={0})
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    jit_update = False
    jit_compute = True

    _modified_stuffs = False

    def __init__(
        self,
        things: Collection[int],
        stuffs: Collection[int],
        allow_unknown_preds_category: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        things_p, stuffs_p = _parse_categories(things, stuffs)
        self.things = things_p
        self.stuffs = stuffs_p
        self.void_color = _get_void_color(things_p, stuffs_p)
        self.cat_id_to_continuous_id = _get_category_id_to_continuous_id(things_p, stuffs_p)
        self.allow_unknown_preds_category = allow_unknown_preds_category
        num_categories = len(things_p) + len(stuffs_p)
        self.add_state("iou_sum", jnp.zeros(num_categories, jnp.float32), dist_reduce_fx="sum")
        self.add_state("true_positives", jnp.zeros(num_categories, jnp.int32), dist_reduce_fx="sum")  # jaxlint: disable=TPU005 — int32 is the TPU-native count dtype (x64 off; int64 would lower to int32), and sample-scale counts stay far below 2^31
        self.add_state("false_positives", jnp.zeros(num_categories, jnp.int32), dist_reduce_fx="sum")  # jaxlint: disable=TPU005 — see true_positives
        self.add_state("false_negatives", jnp.zeros(num_categories, jnp.int32), dist_reduce_fx="sum")  # jaxlint: disable=TPU005 — see true_positives

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        _validate_inputs(preds, target)
        flat_preds = _preprocess_inputs(
            self.things, self.stuffs, preds, self.void_color, self.allow_unknown_preds_category
        )
        flat_target = _preprocess_inputs(self.things, self.stuffs, target, self.void_color, True)
        iou_sum, tp, fp, fn = _panoptic_quality_update(
            flat_preds,
            flat_target,
            self.cat_id_to_continuous_id,
            self.void_color,
            modified_metric_stuffs=self.stuffs if self._modified_stuffs else None,
        )
        return {
            "iou_sum": state["iou_sum"] + iou_sum,
            "true_positives": state["true_positives"] + tp,
            "false_positives": state["false_positives"] + fp,
            "false_negatives": state["false_negatives"] + fn,
        }

    def _compute(self, state: Dict[str, Any]) -> Array:
        return _panoptic_quality_compute(
            state["iou_sum"], state["true_positives"], state["false_positives"], state["false_negatives"]
        )


class ModifiedPanopticQuality(PanopticQuality):
    """Modified PQ: stuff classes scored without segment matching (reference ``panoptic_qualities.py:220``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.detection import ModifiedPanopticQuality
        >>> preds = np.array([[[6, 0], [0, 0], [6, 0], [7, 0]]])
        >>> target = np.array([[[6, 0], [0, 1], [6, 0], [7, 0]]])
        >>> metric = ModifiedPanopticQuality(things={6, 7}, stuffs={0})
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    _modified_stuffs = True
