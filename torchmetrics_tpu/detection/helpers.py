"""Detection input validation (same contract as reference ``src/torchmetrics/detection/helpers.py``).

Structure: a declarative field spec per side (required keys + which fields must share their
leading dimension), checked by one generic pass — rather than per-key inline checks.
"""
from __future__ import annotations

from typing import Dict, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

_GEOMETRY_KEY = {"bbox": "boxes", "segm": "masks"}


def _is_arraylike(x) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray)) or hasattr(x, "shape")


def _leading_dim(x) -> int:
    shape = jnp.shape(x)
    return int(shape[0]) if shape else 0


def _check_sample_dicts(
    side: str, samples: Sequence[Dict], required: Tuple[str, ...], check_lengths: bool = True
) -> None:
    """Every sample dict must carry ``required`` keys; with ``check_lengths`` those fields must
    also agree on their number of instances (shared leading dimension)."""
    for key in required:
        if any(key not in sample for sample in samples):
            raise ValueError(f"Expected all dicts in `{side}` to contain the `{key}` key")
    if not check_lengths:
        return
    for i, sample in enumerate(samples):
        lengths = {key: _leading_dim(sample[key]) for key in required}
        if len(set(lengths.values())) > 1:
            detail = ", ".join(f"{k}={n}" for k, n in lengths.items())
            raise ValueError(
                f"Fields of sample {i} in `{side}` disagree on the number of instances ({detail})"
            )


def _input_validator(
    preds: Sequence[Dict],
    targets: Sequence[Dict],
    iou_type: Union[str, Tuple[str, ...]] = "bbox",
    ignore_score: bool = False,
) -> None:
    """Shape/type contract for list-of-dict detection inputs (reference ``helpers.py:19-81``)."""
    iou_types = (iou_type,) if isinstance(iou_type, str) else tuple(iou_type)
    unknown = [tp for tp in iou_types if tp not in _GEOMETRY_KEY]
    if unknown:
        raise Exception(f"IOU type {iou_types} is not supported")
    geometry = tuple(_GEOMETRY_KEY[tp] for tp in iou_types)

    for side, value in (("preds", preds), ("target", targets)):
        if not isinstance(value, Sequence):
            raise ValueError(f"Expected argument `{side}` to be of type Sequence, but got {value}")
    if len(preds) != len(targets):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, but got {len(preds)} and {len(targets)}"
        )

    # with ignore_score the reference checks preds key presence only, not length agreement
    # (reference helpers.py:51-53 returns before the preds length loop)
    pred_fields = geometry + (("labels",) if ignore_score else ("labels", "scores"))
    _check_sample_dicts("preds", preds, pred_fields, check_lengths=not ignore_score)
    _check_sample_dicts("target", targets, geometry + ("labels",))


def _fix_empty_boxes(boxes) -> jnp.ndarray:
    """Normalise empty inputs to shape (0, 4) (reference ``helpers.py:83-87``)."""
    boxes = jnp.asarray(boxes, jnp.float32)
    if boxes.size == 0:
        return boxes.reshape(0, 4)
    return boxes
