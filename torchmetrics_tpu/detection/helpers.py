"""Detection input validation (reference ``src/torchmetrics/detection/helpers.py``)."""
from __future__ import annotations

from typing import Dict, Sequence

import jax.numpy as jnp
import numpy as np


def _is_arraylike(x) -> bool:
    return isinstance(x, (jnp.ndarray, np.ndarray)) or hasattr(x, "shape")


def _input_validator(
    preds: Sequence[Dict],
    targets: Sequence[Dict],
    iou_type: str = "bbox",
    ignore_score: bool = False,
) -> None:
    """Shape/type contract for list-of-dict detection inputs (reference ``helpers.py:19-81``)."""
    if isinstance(iou_type, str):
        iou_type = (iou_type,)
    name_map = {"bbox": "boxes", "segm": "masks"}
    if any(tp not in name_map for tp in iou_type):
        raise Exception(f"IOU type {iou_type} is not supported")
    item_val_name = [name_map[tp] for tp in iou_type]

    if not isinstance(preds, Sequence):
        raise ValueError(f"Expected argument `preds` to be of type Sequence, but got {preds}")
    if not isinstance(targets, Sequence):
        raise ValueError(f"Expected argument `target` to be of type Sequence, but got {targets}")
    if len(preds) != len(targets):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, but got {len(preds)} and {len(targets)}"
        )
    for k in [*item_val_name, "labels"] + (["scores"] if not ignore_score else []):
        if any(k not in p for p in preds):
            raise ValueError(f"Expected all dicts in `preds` to contain the `{k}` key")
    for k in [*item_val_name, "labels"]:
        if any(k not in p for p in targets):
            raise ValueError(f"Expected all dicts in `target` to contain the `{k}` key")

    for i, item in enumerate(targets):
        for ivn in item_val_name:
            if jnp.shape(item[ivn])[0] != jnp.shape(item["labels"])[0]:
                raise ValueError(
                    f"Input '{ivn}' and labels of sample {i} in targets have a"
                    f" different length (expected {jnp.shape(item[ivn])[0]} labels,"
                    f" got {jnp.shape(item['labels'])[0]})"
                )
    if ignore_score:
        return
    for i, item in enumerate(preds):
        for ivn in item_val_name:
            if not (jnp.shape(item[ivn])[0] == jnp.shape(item["labels"])[0] == jnp.shape(item["scores"])[0]):
                raise ValueError(
                    f"Input '{ivn}', labels and scores of sample {i} in predictions have a"
                    f" different length (expected {jnp.shape(item[ivn])[0]} labels and scores,"
                    f" got {jnp.shape(item['labels'])[0]} labels and {jnp.shape(item['scores'])[0]} scores)"
                )


def _fix_empty_boxes(boxes) -> jnp.ndarray:
    """Normalise empty inputs to shape (0, 4) (reference ``helpers.py:83-87``)."""
    boxes = jnp.asarray(boxes, jnp.float32)
    if boxes.size == 0:
        return boxes.reshape(0, 4)
    return boxes
