"""IoU-family module metrics (reference ``src/torchmetrics/detection/{iou,giou,diou,ciou}.py``)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.detection.helpers import _fix_empty_boxes, _input_validator
from torchmetrics_tpu.functional.detection.iou import (
    box_convert,
    box_iou,
    complete_box_iou,
    distance_box_iou,
    generalized_box_iou,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError


class IntersectionOverUnion(Metric):
    """IoU over matched detection/ground-truth boxes (reference ``detection/iou.py:30``).

    Per-image IoU matrices have data-dependent shapes, so they live as host-side list states
    (``dist_reduce_fx=None`` gather, like the reference); each matrix itself is one fused jnp
    kernel.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.detection import IntersectionOverUnion
        >>> preds = [{"boxes": np.array([[0.0, 0.0, 10.0, 10.0]], np.float32),
        ...           "scores": np.array([0.9], np.float32), "labels": np.array([0])}]
        >>> target = [{"boxes": np.array([[0.0, 0.0, 10.0, 8.0]], np.float32),
        ...            "labels": np.array([0])}]
        >>> metric = IntersectionOverUnion()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()['iou']):.4f}")
        0.8000
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    jit_update = False
    jit_compute = False

    _iou_type: str = "iou"
    _invalid_val: float = -1.0
    _pairwise_fn: Callable = staticmethod(box_iou)

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_threshold: Optional[float] = None,
        class_metrics: bool = False,
        respect_labels: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_threshold = iou_threshold
        if not isinstance(class_metrics, bool):
            raise ValueError('Argument `class_metrics` must be a boolean')
        self.class_metrics = class_metrics
        if not isinstance(respect_labels, bool):
            raise ValueError('Argument `respect_labels` must be a boolean')
        self.respect_labels = respect_labels
        self.add_state("groundtruth_labels", [], dist_reduce_fx=None)
        self.add_state("iou_matrix", [], dist_reduce_fx=None)

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:  # noqa: D102
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric has already been synced. HINT: Did you forget to call `unsync`?"
            )
        _input_validator(preds, target, ignore_score=True)
        for p, t in zip(preds, target):
            det_boxes = self._get_safe_item_values(p["boxes"])
            gt_boxes = self._get_safe_item_values(t["boxes"])
            self._state.lists["groundtruth_labels"].append(jnp.asarray(t["labels"]))
            iou_matrix = type(self)._pairwise_fn(det_boxes, gt_boxes)
            if self.iou_threshold is not None:
                iou_matrix = jnp.where(iou_matrix < self.iou_threshold, self._invalid_val, iou_matrix)
            if self.respect_labels:
                label_eq = jnp.asarray(p["labels"])[:, None] == jnp.asarray(t["labels"])[None, :]
                iou_matrix = jnp.where(label_eq, iou_matrix, self._invalid_val)
            self._state.lists["iou_matrix"].append(iou_matrix)
        self._update_count += 1
        self._update_called = True
        self._computed = None

    def _get_safe_item_values(self, boxes: Array) -> Array:
        boxes = _fix_empty_boxes(boxes)
        if boxes.size > 0:
            boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
        return boxes

    def _update(self, state, *args, **kwargs):  # pragma: no cover - update() is overridden
        raise NotImplementedError

    def _compute(self, state: Dict[str, Any]) -> Dict[str, Array]:
        mats = self._state.lists["iou_matrix"]
        gt_labels = self._state.lists["groundtruth_labels"]
        valid = [m[m != self._invalid_val] for m in mats]
        flat = jnp.concatenate([v.reshape(-1) for v in valid], axis=0) if valid else jnp.zeros((0,))
        score = jnp.mean(flat) if flat.size else jnp.asarray(0.0)
        results = {f"{self._iou_type}": score}
        if self.class_metrics:
            all_labels = (
                np.unique(np.concatenate([np.asarray(g).reshape(-1) for g in gt_labels]))
                if gt_labels
                else np.zeros((0,), np.int64)
            )
            for cl in all_labels.tolist():
                masked_sum, observed = 0.0, 0
                for mat, gl in zip(mats, gt_labels):
                    scores = np.asarray(mat)[:, np.asarray(gl) == cl]
                    sel = scores[scores != self._invalid_val]
                    masked_sum += sel.sum()
                    observed += sel.size
                results[f"{self._iou_type}/cl_{cl}"] = jnp.asarray(masked_sum / observed if observed else 0.0)
        return results

    def compute(self) -> Dict[str, Array]:  # noqa: D102 - dict output, squeeze per entry
        with self.sync_context(dist_sync_fn=self.dist_sync_fn, should_sync=self._to_sync):
            return {k: self._squeeze_if_scalar(v) for k, v in self._compute({}).items()}


class GeneralizedIntersectionOverUnion(IntersectionOverUnion):
    """GIoU (reference ``detection/giou.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.detection import GeneralizedIntersectionOverUnion
        >>> preds = [{"boxes": np.array([[0.0, 0.0, 10.0, 10.0]], np.float32),
        ...           "scores": np.array([0.9], np.float32), "labels": np.array([0])}]
        >>> target = [{"boxes": np.array([[0.0, 0.0, 10.0, 8.0]], np.float32),
        ...            "labels": np.array([0])}]
        >>> metric = GeneralizedIntersectionOverUnion()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()['giou']):.4f}")
        0.8000
    """

    _iou_type = "giou"
    _invalid_val = -1.0
    _pairwise_fn = staticmethod(generalized_box_iou)


class DistanceIntersectionOverUnion(IntersectionOverUnion):
    """DIoU (reference ``detection/diou.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.detection import DistanceIntersectionOverUnion
        >>> preds = [{"boxes": np.array([[0.0, 0.0, 10.0, 10.0]], np.float32),
        ...           "scores": np.array([0.9], np.float32), "labels": np.array([0])}]
        >>> target = [{"boxes": np.array([[0.0, 0.0, 10.0, 8.0]], np.float32),
        ...            "labels": np.array([0])}]
        >>> metric = DistanceIntersectionOverUnion()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()['diou']):.4f}")
        0.7950
    """

    _iou_type = "diou"
    _invalid_val = -1.0
    _pairwise_fn = staticmethod(distance_box_iou)


class CompleteIntersectionOverUnion(IntersectionOverUnion):
    """CIoU (reference ``detection/ciou.py:30``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.detection import CompleteIntersectionOverUnion
        >>> preds = [{"boxes": np.array([[0.0, 0.0, 10.0, 10.0]], np.float32),
        ...           "scores": np.array([0.9], np.float32), "labels": np.array([0])}]
        >>> target = [{"boxes": np.array([[0.0, 0.0, 10.0, 8.0]], np.float32),
        ...            "labels": np.array([0])}]
        >>> metric = CompleteIntersectionOverUnion()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()['ciou']):.4f}")
        0.7949
    """

    _iou_type = "ciou"
    _invalid_val = -2.0  # CIoU can be < -1 (reference ciou.py:102)
    _pairwise_fn = staticmethod(complete_box_iou)
