"""Detection module metrics (reference ``src/torchmetrics/detection/``)."""
from torchmetrics_tpu.detection.iou import (
    CompleteIntersectionOverUnion,
    DistanceIntersectionOverUnion,
    GeneralizedIntersectionOverUnion,
    IntersectionOverUnion,
)
from torchmetrics_tpu.detection.mean_ap import MeanAveragePrecision
from torchmetrics_tpu.detection.panoptic_qualities import ModifiedPanopticQuality, PanopticQuality

__all__ = [
    "CompleteIntersectionOverUnion",
    "DistanceIntersectionOverUnion",
    "GeneralizedIntersectionOverUnion",
    "IntersectionOverUnion",
    "MeanAveragePrecision",
    "ModifiedPanopticQuality",
    "PanopticQuality",
]
