"""Mean Average Precision, COCO protocol (reference ``src/torchmetrics/detection/_mean_ap.py:148``).

The reference's legacy pure-torch implementation is the parity spec (its primary path shells out
to pycocotools C code — ``mean_ap.py:50-70`` — which this build deliberately does not depend on).

TPU redesign: the reference evaluates each (image, class, area) with Python loops over
detections and IoU thresholds (``_mean_ap.py:594-600``). Here every (image, class) group is
padded into fixed-capacity buffers (mask, never drop) and ONE jitted matcher runs the greedy
COCO assignment for ALL groups × 4 area ranges × T IoU thresholds in parallel — a ``lax.scan``
over the detection axis (the only genuinely sequential dimension of the algorithm) with
vectorised masked-argmax matching inside. Buffer sizes round up to powers of two so recompiles
are logarithmic in dataset shape. The cheap ragged precision/recall accumulation stays in numpy.

Geometry is pluggable: ``iou_type="bbox"`` uses box IoU over (N, 4) buffers; ``"segm"``
(reference ``mean_ap.py:104-115,178``) stores binary masks, pads them to a common (H, W), and
computes mask IoU as a single flattened ``dets @ gts.T`` intersection matmul on the MXU — no RLE
encodings needed. Both at once (``iou_type=("bbox", "segm")``) prefix result keys like the
reference. ``extended_summary=True`` returns the reference's extra ``ious`` / ``precision`` /
``recall`` / ``scores`` entries (``mean_ap.py:192-210,536-545``).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

from torchmetrics_tpu.detection.helpers import _fix_empty_boxes, _input_validator
from torchmetrics_tpu.functional.detection.iou import _pairwise_inter_union, box_area, box_convert
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError

_AREA_RANGES = {
    "all": (0.0, 1e5**2),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e5**2),
}


def _validate_iou_types(iou_type: Union[str, Sequence[str]]) -> Tuple[str, ...]:
    types = (iou_type,) if isinstance(iou_type, str) else tuple(iou_type)
    if not types or any(t not in ("bbox", "segm") for t in types):
        raise ValueError(f"Expected argument `iou_type` to be one of ('bbox', 'segm') or a tuple of them, got {iou_type}")
    return types


@functools.partial(jax.jit, static_argnames=("num_thrs",))
def _match_all_groups(
    ious: Array,        # (P, D, G) pairwise IoU, det rows sorted by score desc
    det_valid: Array,   # (P, D) bool
    gt_valid: Array,    # (P, G) bool
    gt_ignore: Array,   # (P, A, G) bool — outside the area range
    thresholds: Array,  # (T,)
    num_thrs: int,
) -> Array:
    """Greedy COCO matching for every (group, area, threshold) in parallel.

    Ignored ground truths are never matchable (legacy-impl semantics,
    ``_mean_ap.py:628-650``: the argmax masks them out entirely).
    """
    num_pairs, num_det, _ = ious.shape
    num_areas = gt_ignore.shape[1]
    matchable0 = gt_valid[:, None, None, :] & ~gt_ignore[:, :, None, :]  # (P, A, 1, G)
    matchable0 = jnp.broadcast_to(matchable0, (num_pairs, num_areas, num_thrs, gt_valid.shape[1]))

    def body(gt_matched, d):
        iou_d = ious[:, d, :][:, None, None, :]  # (P, 1, 1, G)
        masked = jnp.where(matchable0 & ~gt_matched, iou_d, 0.0)
        m = jnp.argmax(masked, axis=-1)  # (P, A, T)
        best = jnp.take_along_axis(masked, m[..., None], axis=-1)[..., 0]
        ok = (best > thresholds[None, None, :]) & det_valid[:, d][:, None, None]
        gt_matched = gt_matched | (
            jax.nn.one_hot(m, masked.shape[-1], dtype=bool) & ok[..., None]
        )
        return gt_matched, ok

    init = jnp.zeros(matchable0.shape, bool)
    _, det_matches = lax.scan(body, init, jnp.arange(num_det))
    return jnp.moveaxis(det_matches, 0, -1)  # (P, A, T, D)


@jax.jit
def _mask_iou_matrix(det_flat: Array, gt_flat: Array):
    """(P, D, HW) x (P, G, HW) boolean masks -> (iou, iod) each (P, D, G), one MXU matmul.

    ``iod`` (intersection over det area) is the COCO crowd-matching IoU
    (``pycocotools`` ``iscrowd=1`` semantics: a crowd region absorbs any detection mostly
    inside it)."""
    det_f = det_flat.astype(jnp.float32)
    gt_f = gt_flat.astype(jnp.float32)
    inter = jnp.einsum("pdh,pgh->pdg", det_f, gt_f, precision="highest")
    area_d = jnp.sum(det_f, axis=-1)
    area_g = jnp.sum(gt_f, axis=-1)
    union = area_d[:, :, None] + area_g[:, None, :] - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1.0), 0.0)
    iod = jnp.where(area_d[:, :, None] > 0, inter / jnp.maximum(area_d[:, :, None], 1.0), 0.0)
    return iou, iod


@jax.jit
def _box_iou_iod(det_buf: Array, gt_buf: Array):
    """(P, D, 4) x (P, G, 4) boxes -> (iou, iod) each (P, D, G)."""
    inter, union = _pairwise_inter_union(det_buf, gt_buf)
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-9), 0.0)
    area_d = box_area(det_buf)[..., :, None]
    iod = jnp.where(area_d > 0, inter / jnp.maximum(area_d, 1e-9), 0.0)
    return iou, iod


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 2 ** int(np.ceil(np.log2(n)))


class MeanAveragePrecision(Metric):
    """mAP / mAR for object detection and instance segmentation (reference ``mean_ap.py:76``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.detection import MeanAveragePrecision
        >>> preds = [{"boxes": np.array([[0.0, 0.0, 10.0, 10.0]], np.float32),
        ...           "scores": np.array([0.9], np.float32), "labels": np.array([0])}]
        >>> target = [{"boxes": np.array([[0.0, 0.0, 10.0, 8.0]], np.float32),
        ...            "labels": np.array([0])}]
        >>> metric = MeanAveragePrecision()
        >>> metric.update(preds, target)
        >>> result = metric.compute()
        >>> print(f"{float(result['map']):.4f} {float(result['map_50']):.4f}")
        0.6000 1.0000
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    jit_update = False
    jit_compute = False

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_type: Union[str, Tuple[str, ...]] = "bbox",
        iou_thresholds: Optional[List[float]] = None,
        rec_thresholds: Optional[List[float]] = None,
        max_detection_thresholds: Optional[List[int]] = None,
        class_metrics: bool = False,
        extended_summary: bool = False,
        average: str = "macro",
        backend: str = "pycocotools",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Argument `box_format` must be one of {allowed_box_formats}, but got {box_format}")
        self.box_format = box_format
        self.iou_types = _validate_iou_types(iou_type)
        self.iou_type = iou_type
        self.iou_thresholds = list(iou_thresholds or np.linspace(0.5, 0.95, 10).round(2).tolist())
        self.rec_thresholds = list(rec_thresholds or np.linspace(0.0, 1.0, 101).round(2).tolist())
        self.max_detection_thresholds = sorted(int(x) for x in (max_detection_thresholds or [1, 10, 100]))
        if not isinstance(class_metrics, bool):
            raise ValueError('Argument `class_metrics` must be a boolean')
        self.class_metrics = class_metrics
        if not isinstance(extended_summary, bool):
            raise ValueError("Expected argument `extended_summary` to be a boolean")
        self.extended_summary = extended_summary
        if average not in ("macro", "micro"):
            raise ValueError(f"Argument `average` must be 'macro' or 'micro', but got {average}")
        self.average = average
        if backend not in ("pycocotools", "faster_coco_eval"):
            raise ValueError(
                f"Argument `backend` must be 'pycocotools' or 'faster_coco_eval', but got {backend}"
            )
        self.backend = backend  # accepted for API parity; evaluation is the built-in XLA matcher
        self.add_state("detections", [], dist_reduce_fx=None)
        self.add_state("detection_masks", [], dist_reduce_fx=None)
        self.add_state("detection_scores", [], dist_reduce_fx=None)
        self.add_state("detection_labels", [], dist_reduce_fx=None)
        self.add_state("groundtruths", [], dist_reduce_fx=None)
        self.add_state("groundtruth_masks", [], dist_reduce_fx=None)
        self.add_state("groundtruth_labels", [], dist_reduce_fx=None)
        self.add_state("groundtruth_crowds", [], dist_reduce_fx=None)
        self.add_state("groundtruth_area", [], dist_reduce_fx=None)

    def update(self, preds: List[Dict[str, Array]], target: List[Dict[str, Array]]) -> None:  # noqa: D102
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric has already been synced. HINT: Did you forget to call `unsync`?"
            )
        _input_validator(preds, target, iou_type=self.iou_types)
        # validate optional COCO fields BEFORE any state append: a mid-loop failure must not
        # leave the list states partially mutated/misaligned. Lengths are static shapes —
        # read them without building device arrays (this is the per-step update hot path)
        def _flat_len(v) -> int:
            shape = getattr(v, "shape", None)
            return int(np.prod(shape)) if shape is not None else len(v)

        for item in target:
            n_labels = _flat_len(item["labels"])
            for key in ("iscrowd", "area"):
                val = item.get(key)
                if val is not None and _flat_len(val) != n_labels:
                    raise ValueError(
                        f"Input '{key}' and labels of a sample in targets have different"
                        f" lengths ({_flat_len(val)} vs {n_labels})"
                    )
        for item in preds:
            if "bbox" in self.iou_types:
                self._state.lists["detections"].append(self._get_safe_item_values(item["boxes"]))
            if "segm" in self.iou_types:
                self._state.lists["detection_masks"].append(jnp.asarray(item["masks"], bool))
            self._state.lists["detection_labels"].append(jnp.asarray(item["labels"]).reshape(-1))
            self._state.lists["detection_scores"].append(jnp.asarray(item["scores"]).reshape(-1))
        for item in target:
            if "bbox" in self.iou_types:
                self._state.lists["groundtruths"].append(self._get_safe_item_values(item["boxes"]))
            if "segm" in self.iou_types:
                self._state.lists["groundtruth_masks"].append(jnp.asarray(item["masks"], bool))
            labels = jnp.asarray(item["labels"]).reshape(-1)
            self._state.lists["groundtruth_labels"].append(labels)
            # optional COCO annotation fields (reference mean_ap.py:507-508)
            for key, default_dtype, state_name in (
                ("iscrowd", jnp.int32, "groundtruth_crowds"),
                ("area", jnp.float32, "groundtruth_area"),
            ):
                val = item.get(key)
                val = (
                    jnp.zeros(labels.shape, default_dtype)
                    if val is None
                    else jnp.asarray(val).reshape(-1)  # lengths validated up front
                )
                self._state.lists[state_name].append(val)
        self._update_count += 1
        self._update_called = True
        self._computed = None

    def _get_safe_item_values(self, boxes: Array) -> Array:
        boxes = _fix_empty_boxes(boxes)
        if boxes.size > 0:
            boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
        return boxes

    def _update(self, state, *args, **kwargs):  # pragma: no cover - update() is overridden
        raise NotImplementedError

    def _get_classes(self) -> List[int]:
        labels = self._state.lists["detection_labels"] + self._state.lists["groundtruth_labels"]
        if not labels:
            return []
        cat = np.concatenate([np.asarray(x).reshape(-1) for x in labels])
        return np.unique(cat).astype(np.int64).tolist()

    # ------------------------------------------------------------------ geometry access
    def _geometries(self, i_type: str):
        """Per-image (det geometry, gt geometry) numpy lists for one iou type."""
        if i_type == "bbox":
            dets = [np.asarray(d).reshape(-1, 4) for d in self._state.lists["detections"]]
            gts = [np.asarray(g).reshape(-1, 4) for g in self._state.lists["groundtruths"]]
        else:
            def _to_np(m):
                arr = np.asarray(m)  # ONE host transfer per stored stack
                return arr.reshape((-1,) + arr.shape[-2:]) if arr.size else np.zeros((0, 1, 1), bool)

            dets = [_to_np(m) for m in self._state.lists["detection_masks"]]
            gts = [_to_np(m) for m in self._state.lists["groundtruth_masks"]]
        return dets, gts

    # ------------------------------------------------------------------ compute
    def _build_groups(self, classes: List[int], i_type: str, micro: bool = False):
        """Group detections/gts per (image, class); sort dets by score desc; pad to capacity.

        ``micro=True`` merges every label into one class (reference ``mean_ap.py:589-594``).
        """
        max_det = self.max_detection_thresholds[-1]
        dets, gts = self._geometries(i_type)
        det_scores = [np.asarray(s) for s in self._state.lists["detection_scores"]]
        det_labels = [np.asarray(l) for l in self._state.lists["detection_labels"]]
        gt_labels = [np.asarray(l) for l in self._state.lists["groundtruth_labels"]]
        gt_crowds = [np.asarray(c) for c in self._state.lists["groundtruth_crowds"]]
        gt_area_over = [np.asarray(a) for a in self._state.lists["groundtruth_area"]]
        if micro:
            det_labels = [np.zeros_like(l) for l in det_labels]
            gt_labels = [np.zeros_like(l) for l in gt_labels]

        groups = []  # (cls_idx, img_idx, det geom sorted, det scores sorted, gt geom, crowd, area)
        for cls_idx, cls in enumerate(classes):
            for i in range(len(gts)):
                d_mask = det_labels[i] == cls
                g_mask = gt_labels[i] == cls
                if not d_mask.any() and not g_mask.any():
                    continue
                s = det_scores[i][d_mask]
                order = np.argsort(-s, kind="stable")[:max_det]
                groups.append((
                    cls_idx, i, dets[i][d_mask][order], s[order], gts[i][g_mask],
                    gt_crowds[i][g_mask], gt_area_over[i][g_mask],
                ))

        if not groups:
            return None
        cap_d = _next_pow2(max(g[2].shape[0] for g in groups))
        cap_g = _next_pow2(max(g[4].shape[0] for g in groups))
        num = len(groups)
        scores = np.full((num, cap_d), -np.inf, np.float32)
        det_valid = np.zeros((num, cap_d), bool)
        gt_valid = np.zeros((num, cap_g), bool)
        gt_crowd = np.zeros((num, cap_g), bool)
        gt_area = np.zeros((num, cap_g), np.float64)
        cls_of = np.empty(num, np.int64)
        img_of = np.empty(num, np.int64)
        det_geoms: List[np.ndarray] = []
        gt_geoms: List[np.ndarray] = []
        for j, (cls_idx, img_idx, dg, sc, gg, crowd, area_over) in enumerate(groups):
            cls_of[j] = cls_idx
            img_of[j] = img_idx
            nd, ng = dg.shape[0], gg.shape[0]
            det_geoms.append(dg)
            gt_geoms.append(gg)
            scores[j, :nd] = sc
            det_valid[j, :nd] = True
            gt_valid[j, :ng] = True
            gt_crowd[j, :ng] = crowd.astype(bool)
            gt_area[j, :ng] = area_over
        return cls_of, img_of, det_geoms, scores, det_valid, gt_geoms, gt_valid, cap_d, cap_g, gt_crowd, gt_area

    # dense mask-IoU work is chunked so device/host buffers stay bounded regardless of dataset
    # size: each chunk pads only ITS groups to its own (H, W) and detection/gt capacities
    _SEGM_CHUNK_ELEMS = 1 << 28  # ~256M bool elements per chunk buffer (~256 MB)

    def _pairwise_iou_all(
        self,
        det_geoms: List[np.ndarray],
        gt_geoms: List[np.ndarray],
        i_type: str,
        cap_d: int,
        cap_g: int,
        need_iod: bool = False,
    ):
        """(P, cap_d, cap_g) (IoU, intersection-over-det) matrices; pads in per-chunk buffers,
        never a global mask tensor. ``iod`` is None unless requested (crowd gts present) — it
        doubles the D2H transfer and host buffering of the memory-bound stage."""
        num = len(det_geoms)
        out = np.zeros((num, cap_d, cap_g), np.float32)
        out_iod = np.zeros((num, cap_d, cap_g), np.float32) if need_iod else None
        if i_type == "bbox":
            det_buf = np.zeros((num, cap_d, 4), np.float32)
            gt_buf = np.zeros((num, cap_g, 4), np.float32)
            for j, (dg, gg) in enumerate(zip(det_geoms, gt_geoms)):
                det_buf[j, : dg.shape[0]] = dg
                gt_buf[j, : gg.shape[0]] = gg
            iou, iod = _box_iou_iod(jnp.asarray(det_buf), jnp.asarray(gt_buf))
            return np.asarray(iou), (np.asarray(iod) if need_iod else None)
        start = 0
        while start < num:
            # chunk size bounded by the PADDED buffer footprint: members pad to the chunk-wide
            # max (H, W), so the budget must use the running max, not each member's own size
            end = start
            run_h = run_w = 1
            while end < num:
                h = max(det_geoms[end].shape[1] if det_geoms[end].size else 1,
                        gt_geoms[end].shape[1] if gt_geoms[end].size else 1)
                w = max(det_geoms[end].shape[2] if det_geoms[end].size else 1,
                        gt_geoms[end].shape[2] if gt_geoms[end].size else 1)
                new_h, new_w = max(run_h, h), max(run_w, w)
                padded_elems = (end - start + 1) * (cap_d + cap_g) * new_h * new_w
                if end > start and padded_elems > self._SEGM_CHUNK_ELEMS:
                    break
                run_h, run_w = new_h, new_w
                end += 1
            chunk_d = det_geoms[start:end]
            chunk_g = gt_geoms[start:end]
            max_h = max(max(d.shape[1] if d.size else 1, g.shape[1] if g.size else 1) for d, g in zip(chunk_d, chunk_g))
            max_w = max(max(d.shape[2] if d.size else 1, g.shape[2] if g.size else 1) for d, g in zip(chunk_d, chunk_g))
            n = end - start
            det_buf = np.zeros((n, cap_d, max_h, max_w), bool)
            gt_buf = np.zeros((n, cap_g, max_h, max_w), bool)
            for jj, (dg, gg) in enumerate(zip(chunk_d, chunk_g)):
                det_buf[jj, : dg.shape[0], : dg.shape[1], : dg.shape[2]] = dg
                gt_buf[jj, : gg.shape[0], : gg.shape[1], : gg.shape[2]] = gg
            iou, iod = _mask_iou_matrix(
                jnp.asarray(det_buf.reshape(n, cap_d, -1)),
                jnp.asarray(gt_buf.reshape(n, cap_g, -1)),
            )
            out[start:end] = np.asarray(iou)
            if need_iod:
                out_iod[start:end] = np.asarray(iod)
            start = end
        return out, out_iod

    @staticmethod
    def _geom_areas(geoms: List[np.ndarray], cap: int, i_type: str) -> np.ndarray:
        out = np.zeros((len(geoms), cap), np.float64)
        for j, g in enumerate(geoms):
            if not g.shape[0]:
                continue
            if i_type == "bbox":
                out[j, : g.shape[0]] = np.asarray(box_area(jnp.asarray(g)))
            else:
                out[j, : g.shape[0]] = g.reshape(g.shape[0], -1).sum(axis=-1)
        return out

    def _compute_one_type(self, classes: List[int], i_type: str, micro: bool = False):
        """precision (T,R,K,A,M), recall (T,K,A,M), scores (T,R,K,A,M), ious dict for one type."""
        num_t = len(self.iou_thresholds)
        num_r = len(self.rec_thresholds)
        num_k = len(classes)
        num_a = len(_AREA_RANGES)
        num_m = len(self.max_detection_thresholds)
        precision = -np.ones((num_t, num_r, num_k, num_a, num_m))
        recall = -np.ones((num_t, num_k, num_a, num_m))
        score_arr = -np.ones((num_t, num_r, num_k, num_a, num_m))
        ious_out: Dict[Tuple[int, int], Array] = {}

        if self.extended_summary:
            # the reference returns an entry for EVERY (image, class) pair (cocoeval.ious);
            # pairs with no group get an empty matrix, group pairs are overwritten below
            num_imgs = len(self._state.lists["detection_labels"])
            empty = jnp.zeros((0, 0), jnp.float32)
            ious_out = {(i, c): empty for i in range(num_imgs) for c in classes}

        built = self._build_groups(classes, i_type, micro=micro) if classes else None
        if built is not None:
            (cls_of, img_of, det_geoms, scores, det_valid, gt_geoms, gt_valid,
             cap_d, cap_g, gt_crowd, gt_area_over) = built
            # one device program: pairwise IoU + greedy matching for all groups/areas/thresholds
            ious_np, iod_np = self._pairwise_iou_all(
                det_geoms, gt_geoms, i_type, cap_d, cap_g, need_iod=bool((gt_crowd & gt_valid).any())
            )
            ious = jnp.where(
                det_valid[:, :, None] & gt_valid[:, None, :], jnp.asarray(ious_np), 0.0
            )
            if self.extended_summary:
                for j in range(ious_np.shape[0]):
                    nd = int(det_valid[j].sum())
                    ng = int(gt_valid[j].sum())
                    ious_out[(int(img_of[j]), classes[int(cls_of[j])])] = jnp.asarray(
                        ious_np[j, :nd, :ng], jnp.float32
                    )
            gt_areas = self._geom_areas(gt_geoms, cap_g, i_type)
            # explicit COCO annotation areas override the geometry-derived ones when positive
            gt_areas = np.where(gt_area_over > 0, gt_area_over, gt_areas)
            det_areas = self._geom_areas(det_geoms, cap_d, i_type)
            ranges = np.asarray(list(_AREA_RANGES.values()))  # (A, 2)
            # crowd ground truths are ignore-targets in every area range (pycocotools _ignore)
            gt_ignore = (
                (gt_areas[:, None, :] < ranges[None, :, 0:1])
                | (gt_areas[:, None, :] > ranges[None, :, 1:2])
                | gt_crowd[:, None, :]
            )  # (P, A, G)
            det_outside = (det_areas[:, None, :] < ranges[None, :, 0:1]) | (
                det_areas[:, None, :] > ranges[None, :, 1:2]
            )  # (P, A, D)
            det_matches = np.asarray(
                _match_all_groups(
                    ious,
                    jnp.asarray(det_valid),
                    jnp.asarray(gt_valid),
                    jnp.asarray(gt_ignore),
                    jnp.asarray(self.iou_thresholds, jnp.float32),
                    num_t,
                )
            )  # (P, A, T, D)
            # crowd absorption (pycocotools iscrowd semantics): an unmatched detection whose
            # intersection-over-own-area with any crowd gt clears the threshold is ignored,
            # not a false positive; crowd regions absorb unlimited detections. Reduce IoD over
            # crowd gts FIRST so no (P, T, D, G) temporary ever materialises.
            crowd_mask = gt_crowd & gt_valid  # (P, G)
            if iod_np is not None and crowd_mask.any():
                thr = np.asarray(self.iou_thresholds)  # (T,)
                best_crowd_iod = np.where(crowd_mask[:, None, :], iod_np, 0.0).max(axis=-1)  # (P, D)
                # pycocotools compares against min(t, 1-1e-10), i.e. iod >= t matches; the
                # regular matcher keeps the legacy impl's strict > (its declared parity spec)
                crowd_absorb = best_crowd_iod[:, None, :] > thr[None, :, None] - 1e-10  # (P, T, D)
            else:
                crowd_absorb = np.zeros((det_valid.shape[0], num_t, det_valid.shape[1]), bool)
            # unmatched detections outside the area range OR absorbed by a crowd are ignored
            # (_mean_ap.py:609-614 + pycocotools dtIg)
            det_ignore = (
                ~det_matches
                & (det_outside[:, :, None, :] | crowd_absorb[:, None, :, :])
                & det_valid[:, None, None, :]
            )

            rec_thrs = np.asarray(self.rec_thresholds)
            eps = np.finfo(np.float64).eps
            for k in range(num_k):
                sel = cls_of == k
                if not sel.any():
                    continue
                g_scores = scores[sel]          # (Pk, D)
                g_valid = det_valid[sel]
                g_matches = det_matches[sel]    # (Pk, A, T, D)
                g_ignore = det_ignore[sel]
                g_gt_valid = gt_valid[sel]
                g_gt_ignore = gt_ignore[sel]
                for a in range(num_a):
                    npig = int((g_gt_valid & ~g_gt_ignore[:, a]).sum())
                    if npig == 0:
                        continue
                    for mi, max_det in enumerate(self.max_detection_thresholds):
                        keep = g_valid[:, :max_det]  # (Pk, min(D, maxdet))
                        flat_scores = g_scores[:, :max_det][keep]
                        order = np.argsort(-flat_scores, kind="stable")
                        sorted_scores = flat_scores[order]
                        matches = g_matches[:, a, :, :max_det]
                        ignore = g_ignore[:, a, :, :max_det]
                        # (T, N) in global score order
                        tps_all = np.stack([matches[:, t][keep][order] for t in range(num_t)])
                        ign_all = np.stack([ignore[:, t][keep][order] for t in range(num_t)])
                        tps = tps_all & ~ign_all
                        fps = ~tps_all & ~ign_all
                        tp_sum = np.cumsum(tps, axis=1).astype(np.float64)
                        fp_sum = np.cumsum(fps, axis=1).astype(np.float64)
                        for t in range(num_t):
                            tp = tp_sum[t]
                            fp = fp_sum[t]
                            tp_len = len(tp)
                            rc = tp / npig
                            pr = tp / (fp + tp + eps)
                            recall[t, k, a, mi] = rc[-1] if tp_len else 0
                            # monotone precision envelope (the reference's zigzag loop fixpoint)
                            pr = np.maximum.accumulate(pr[::-1])[::-1]
                            prec = np.zeros(num_r)
                            scr = np.zeros(num_r)
                            inds = np.searchsorted(rc, rec_thrs, side="left")
                            num_inds = int(inds.argmax()) if (tp_len == 0 or inds.max() >= tp_len) else num_r
                            inds = inds[:num_inds]
                            prec[:num_inds] = pr[inds]
                            scr[:num_inds] = sorted_scores[inds] if tp_len else 0
                            precision[t, :, k, a, mi] = prec
                            score_arr[t, :, k, a, mi] = scr

        return precision, recall, score_arr, ious_out

    def _compute(self, state: Dict[str, Any]) -> Dict[str, Array]:
        classes = self._get_classes()
        num_k = len(classes)
        micro = self.average == "micro"
        results: Dict[str, Any] = {}
        for i_type in self.iou_types:
            prefix = "" if len(self.iou_types) == 1 else f"{i_type}_"
            # micro averaging merges every label into one class for the headline stats
            # (reference mean_ap.py:589-594); per-class stats below always run macro
            eval_classes = [0] if micro and classes else classes
            precision, recall, score_arr, ious_out = self._compute_one_type(
                eval_classes, i_type, micro=micro
            )
            for key, val in self._summarize_results(precision, recall).items():
                results[f"{prefix}{key}"] = val

            map_per_class = np.asarray([-1.0])
            mar_per_class = np.asarray([-1.0])
            if self.class_metrics and num_k:
                m_precision, m_recall, _, _ = (
                    self._compute_one_type(classes, i_type) if micro else (precision, recall, None, None)
                )
                maps, mars = [], []
                for k in range(num_k):
                    cls_res = self._summarize_results(m_precision[:, :, k : k + 1], m_recall[:, k : k + 1])
                    maps.append(float(cls_res["map"]))
                    mars.append(float(cls_res[f"mar_{self.max_detection_thresholds[-1]}"]))
                map_per_class = np.asarray(maps, np.float32)
                mar_per_class = np.asarray(mars, np.float32)
            results[f"{prefix}map_per_class"] = jnp.asarray(map_per_class)
            results[f"{prefix}mar_{self.max_detection_thresholds[-1]}_per_class"] = jnp.asarray(mar_per_class)
            if self.extended_summary:
                results[f"{prefix}ious"] = ious_out
                results[f"{prefix}precision"] = jnp.asarray(precision, jnp.float32)
                results[f"{prefix}recall"] = jnp.asarray(recall, jnp.float32)
                results[f"{prefix}scores"] = jnp.asarray(score_arr, jnp.float32)
        results["classes"] = jnp.asarray(np.asarray(classes, np.int32))
        return results

    def _summarize(
        self,
        precision: np.ndarray,
        recall: np.ndarray,
        avg_prec: bool,
        iou_threshold: Optional[float] = None,
        area_range: str = "all",
        max_dets: int = 100,
    ) -> float:
        """Mean over valid (> -1) entries of the requested slice (reference ``_mean_ap.py:652-696``)."""
        a = list(_AREA_RANGES).index(area_range)
        m = self.max_detection_thresholds.index(max_dets)
        if avg_prec:
            prec = precision[..., a, m]
        else:
            prec = recall[..., a, m]
        if iou_threshold is not None:
            t = self.iou_thresholds.index(iou_threshold)
            prec = prec[t]
        valid = prec[prec > -1]
        return float(valid.mean()) if valid.size else -1.0

    def _summarize_results(self, precision: np.ndarray, recall: np.ndarray) -> Dict[str, Array]:
        last = self.max_detection_thresholds[-1]
        out: Dict[str, Array] = {}
        out["map"] = self._summarize(precision, recall, True, max_dets=last)
        out["map_50"] = (
            self._summarize(precision, recall, True, iou_threshold=0.5, max_dets=last)
            if 0.5 in self.iou_thresholds
            else -1.0
        )
        out["map_75"] = (
            self._summarize(precision, recall, True, iou_threshold=0.75, max_dets=last)
            if 0.75 in self.iou_thresholds
            else -1.0
        )
        for area in ("small", "medium", "large"):
            out[f"map_{area}"] = self._summarize(precision, recall, True, area_range=area, max_dets=last)
        for max_det in self.max_detection_thresholds:
            out[f"mar_{max_det}"] = self._summarize(precision, recall, False, max_dets=max_det)
        for area in ("small", "medium", "large"):
            out[f"mar_{area}"] = self._summarize(precision, recall, False, area_range=area, max_dets=last)
        return {k: jnp.asarray(v, jnp.float32) for k, v in out.items()}

    def compute(self) -> Dict[str, Array]:  # noqa: D102 - dict output, squeeze per entry
        with self.sync_context(dist_sync_fn=self.dist_sync_fn, should_sync=self._to_sync):
            return {
                k: v if isinstance(v, dict) else self._squeeze_if_scalar(v)
                for k, v in self._compute({}).items()
            }
