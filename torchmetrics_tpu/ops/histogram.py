"""Bincount / confusion-matrix kernels, MXU-first.

Design (vs reference ``src/torchmetrics/utilities/data.py:169-199`` and
``functional/classification/stat_scores.py:405-418``):

- For small cardinality ``C`` (the common metrics case: num_classes, num_thresholds buckets) the
  count is computed as ``one_hot(x).T @ weights`` — a dense (C, N) x (N,) matmul that XLA tiles
  onto the MXU with bf16/f32 accumulation. No scatter, fully deterministic, fuses with upstream
  elementwise work.
- Above ``_ONEHOT_MAX_CARDINALITY`` the one-hot would cost N*C HBM, so we switch to
  ``jax.ops.segment_sum`` (XLA scatter-add) which is O(N + C).

Both paths are shape-static and safe under ``jit``/``shard_map``.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

# One-hot matmul is faster than scatter on TPU until the (N, C) one-hot stops fitting in VMEM
# tiles; 2048 keeps the per-tile footprint small while covering every metrics use-case
# (num_classes, 2*2*T threshold buckets, contingency rows).
_ONEHOT_MAX_CARDINALITY = 2048


_BINCOUNT_BACKEND = "xla"  # "xla" (one-hot matmul / segment-sum) or "pallas" (custom kernel)


def set_bincount_backend(backend: str) -> None:
    """Select the unweighted-bincount lowering: ``"xla"`` (default) or ``"pallas"``.

    The Pallas kernel (``ops.pallas_hist``) accumulates per-bin partial counts in VMEM over a
    sample×bin grid — measured at parity with the one-hot matmul on v5e (both HBM-bound), kept
    as the tuning point for shapes where XLA's lowering is weak.
    """
    if backend not in ("xla", "pallas"):
        raise ValueError(f"bincount backend must be 'xla' or 'pallas', got {backend!r}")
    global _BINCOUNT_BACKEND
    _BINCOUNT_BACKEND = backend


def bincount(x: Array, length: int, dtype=jnp.int32) -> Array:
    """Count occurrences of each int value in ``[0, length)``; out-of-range values are dropped.

    Returns an int array of shape ``(length,)``. Static ``length`` required (XLA).
    """
    if _BINCOUNT_BACKEND == "pallas":
        from torchmetrics_tpu.ops.pallas_hist import bincount_pallas

        try:
            return bincount_pallas(x, length).astype(dtype)
        except Exception:  # pallas lowering unavailable on this platform → XLA path
            pass
    return bincount_weighted(x, length, weights=None, dtype=dtype)


def bincount_weighted(x: Array, length: int, weights: Optional[Array] = None, dtype=None) -> Array:
    """Weighted bincount; ``weights=None`` counts 1 per element.

    Out-of-range / negative indices (e.g. masked ``ignore_index`` entries remapped to -1) are
    dropped on both paths: the one-hot of an out-of-range index is all-zero, and the segment-sum
    path clips with a zero weight.
    """
    x = jnp.reshape(x, (-1,))
    valid = (x >= 0) & (x < length)
    if weights is None:
        w = valid.astype(jnp.float32)
        out_dtype = dtype or jnp.int32
    else:
        w = jnp.reshape(weights, (-1,)) * valid.astype(weights.dtype)
        out_dtype = dtype or weights.dtype
    if length <= _ONEHOT_MAX_CARDINALITY:
        # f32 accumulation: exact up to 2^24 (~16.7M) occurrences per bin. Above that, use the
        # Pallas backend (int32 accumulation) via set_bincount_backend("pallas").
        oh = jax.nn.one_hot(x, length, dtype=jnp.float32)  # (N, C); all-zero row if out of range
        counts = jnp.matmul(w[None, :], oh, precision="highest")[0]  # (C,) on the MXU
    else:
        idx = jnp.clip(x, 0, length - 1)
        counts = jax.ops.segment_sum(w.astype(jnp.float32), idx, num_segments=length)
    return counts.astype(out_dtype)


def hist_pair(idx: Array, pos_w: Array, neg_w: Array, length: int) -> Array:
    """``(2, length)`` weighted counts of ``idx`` under two weight streams — the curve
    sketch's accumulation kernel (``torchmetrics_tpu.sketch.hist``).

    One fused launch either way: the XLA path stacks both weight streams into a single
    ``(2, N) @ (N, C)`` one-hot matmul on the MXU (segment-sum above the one-hot budget);
    the Pallas backend (``set_bincount_backend("pallas")``) runs the VMEM-tiled
    scatter-add twin (``ops.pallas_hist.hist_pair_pallas``) where both streams accumulate
    against one in-register index compare. Out-of-range indices are dropped on every
    path; f32 accumulation (exact to 2^24 unit weights per bin).
    """
    idx = jnp.reshape(idx, (-1,))
    pos_w = jnp.reshape(pos_w, (-1,)).astype(jnp.float32)
    neg_w = jnp.reshape(neg_w, (-1,)).astype(jnp.float32)
    if _BINCOUNT_BACKEND == "pallas":
        from torchmetrics_tpu.ops.pallas_hist import hist_pair_pallas

        try:
            return hist_pair_pallas(idx, pos_w, neg_w, length)
        except Exception:  # pallas lowering unavailable on this platform → XLA path
            pass
    valid = (idx >= 0) & (idx < length)
    w = jnp.stack([pos_w, neg_w]) * valid.astype(jnp.float32)[None, :]  # (2, N)
    if length <= _ONEHOT_MAX_CARDINALITY:
        oh = jax.nn.one_hot(idx, length, dtype=jnp.float32)  # (N, C)
        return jnp.matmul(w, oh, precision="highest")  # (2, C) on the MXU
    clipped = jnp.clip(idx, 0, length - 1)
    return jnp.stack([
        jax.ops.segment_sum(w[0], clipped, num_segments=length),
        jax.ops.segment_sum(w[1], clipped, num_segments=length),
    ])


def confusion_matrix_update(
    preds: Array,
    target: Array,
    num_classes: int,
    weights: Optional[Array] = None,
    dtype=jnp.int32,
) -> Array:
    """(C, C) confusion-matrix contribution of a batch of int labels.

    The reference fuses ``target * C + preds`` and bincounts (``stat_scores.py:405-418``); on TPU
    we instead compute ``one_hot(target).T @ one_hot(preds)`` — a (C, N) x (N, C) matmul on the
    MXU — for small C, falling back to the fused-index segment-sum for large C. ``weights`` (e.g.
    an ignore-index mask) multiplies per-sample contributions.
    """
    preds = jnp.reshape(preds, (-1,))
    target = jnp.reshape(target, (-1,))
    valid = (preds >= 0) & (preds < num_classes) & (target >= 0) & (target < num_classes)
    w = valid.astype(jnp.float32) if weights is None else jnp.reshape(weights, (-1,)).astype(jnp.float32) * valid
    if num_classes <= _ONEHOT_MAX_CARDINALITY // 2:  # two one-hots live at once → half the budget
        oh_t = jax.nn.one_hot(target, num_classes, dtype=jnp.float32)  # (N, C)
        oh_p = jax.nn.one_hot(preds, num_classes, dtype=jnp.float32)  # (N, C)
        cm = jnp.matmul((oh_t * w[:, None]).T, oh_p, precision="highest")  # (C, C)
    else:
        fused = jnp.clip(target, 0, num_classes - 1) * num_classes + jnp.clip(preds, 0, num_classes - 1)
        cm = jax.ops.segment_sum(w, fused, num_segments=num_classes * num_classes)
        cm = jnp.reshape(cm, (num_classes, num_classes))
    return cm.astype(dtype)
