"""Pallas threshold-counts kernel for the binned-curve family.

The XLA formulation (``functional/classification/precision_recall_curve.py:_indicator_counts``)
lowers ``tp[t] = Σ_i pos_i · [score_i >= thr_t]`` as a ``(2, N) @ (N, T)`` dot whose RHS is a
broadcast compare. This kernel computes the same counts with an explicit VMEM pipeline: each
grid step loads a ``(ROWS, 128)`` tile of scores/weights, builds the ``(tile, 128)`` threshold
indicator in registers, reduces it on the spot, and accumulates into a ``(2·thr_rows, 128)``
output block that stays resident across the whole sample grid — the (N, T) indicator never
exists anywhere, in VMEM or HBM.

Same contract as ``_indicator_counts`` restricted to one class: f32 accumulation (exact to
2^24 ones per bucket), masked samples carried as zero weights. Used via
``set_curve_backend("pallas")``; non-TPU platforms run in interpret mode, and the caller falls
back to the dot path on any kernel failure.

Measured on v5e (1M samples, T=200, fori-slope device rate): this VPU formulation reaches
~0.7G samples/s vs ~2.6G for the XLA dot — the compare-into-dot fusion keeps the reduction on
the MXU, which the elementwise compare+multiply+reduce here cannot match (Mosaic rejects the
flattened-operand layout an in-kernel MXU dot would need). The kernel stays as the
deterministic-layout tuning point and the template for shapes where the dot's operand layout
is weak; the XLA dot remains the default.

For STREAMING accumulation the sketch subsystem sidesteps this kernel's O(N·T) compare
entirely: ``approx="sketch"`` buckets each score once into a weighted histogram pair
(``ops/pallas_hist.hist_pair_pallas`` — the fused scatter-add twin of the bincount kernel,
O(N·bins/128) VPU work shared across ALL thresholds) and reconstructs the threshold counts
as an O(bins) suffix sum at compute (``torchmetrics_tpu.sketch.hist``, docs/sketches.md).
This kernel remains the one-shot exact path for explicit non-uniform threshold grids.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

_LANES = 128
_ROWS = 32  # sample tile = (32, 128) = 4096 scores per grid step


def _curve_counts_kernel(scores_ref, pos_ref, neg_ref, thr_ref, out_ref):
    sample_step = pl.program_id(0)

    @pl.when(sample_step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    s = scores_ref[...]  # (ROWS, LANES) f32
    p = pos_ref[...]
    n = neg_ref[...]
    num_thr_rows = thr_ref.shape[0]
    for r in range(num_thr_rows):  # static unroll: T is small (thr rows = ceil(T/128))
        thr = thr_ref[r, :]  # (LANES,)
        ind = (s[:, :, None] >= thr[None, None, :]).astype(jnp.float32)  # (ROWS, LANES, LANES)
        out_ref[2 * r, :] += jnp.sum(p[:, :, None] * ind, axis=(0, 1))
        out_ref[2 * r + 1, :] += jnp.sum(n[:, :, None] * ind, axis=(0, 1))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _curve_counts_impl(scores, pos, neg, thr_rows, interpret: bool) -> Array:
    n = scores.shape[0]
    num_sample_blocks = n // (_ROWS * _LANES)
    num_thr_rows = thr_rows.shape[0]
    shaped = lambda x: x.reshape(num_sample_blocks * _ROWS, _LANES)
    return pl.pallas_call(
        _curve_counts_kernel,
        grid=(num_sample_blocks,),
        in_specs=[
            pl.BlockSpec((_ROWS, _LANES), lambda s: (s, 0)),
            pl.BlockSpec((_ROWS, _LANES), lambda s: (s, 0)),
            pl.BlockSpec((_ROWS, _LANES), lambda s: (s, 0)),
            pl.BlockSpec((num_thr_rows, _LANES), lambda s: (0, 0)),
        ],
        out_specs=pl.BlockSpec((2 * num_thr_rows, _LANES), lambda s: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2 * num_thr_rows, _LANES), jnp.float32),
        interpret=interpret,
    )(shaped(scores), shaped(pos), shaped(neg), thr_rows)


def curve_counts_pallas(
    scores: Array, pos: Array, neg: Array, thresholds: Array
) -> Tuple[Array, Array]:
    """(tp (T,), fp (T,)) threshold counts; the Pallas twin of ``_indicator_counts`` at C=1.

    Pads samples to a full tile with zero weights (a zero-weight sample contributes to no
    bucket) and thresholds to lane width with +inf (no score reaches them; sliced off).
    """
    scores = jnp.asarray(scores, jnp.float32).reshape(-1)
    pos = jnp.asarray(pos, jnp.float32).reshape(-1)
    neg = jnp.asarray(neg, jnp.float32).reshape(-1)
    t = thresholds.shape[0]
    block = _ROWS * _LANES
    n_pad = max(((scores.size + block - 1) // block) * block, block)
    t_rows = (t + _LANES - 1) // _LANES

    def pad_to(x, fill):
        return jnp.full((n_pad,), fill, jnp.float32).at[: x.size].set(x)

    thr_rows = jnp.full((t_rows * _LANES,), jnp.inf, jnp.float32).at[:t].set(
        jnp.asarray(thresholds, jnp.float32)
    ).reshape(t_rows, _LANES)
    interpret = jax.default_backend() != "tpu"
    out = _curve_counts_impl(pad_to(scores, 0.0), pad_to(pos, 0.0), pad_to(neg, 0.0), thr_rows, interpret)
    tp = out[0::2].reshape(-1)[:t]
    fp = out[1::2].reshape(-1)[:t]
    return tp, fp
