"""Pallas bincount kernel (SURVEY §2.9: the named Pallas candidate — XLA's native lowering of
bincount is either a scatter-add (non-deterministic on some backends, serialised on TPU) or a
materialised one-hot).

Design: grid over (sample blocks × bin rows). Each step loads a ``(ROWS, 128)`` tile of indices
into VMEM, compares it against one 128-wide row of bin ids with a broadcasted iota — pure VPU
work, no HBM one-hot — and accumulates the 128 partial counts into the output tile, revisiting
the same output block across the sample-grid dimension. Counts layout ``(num_bin_rows, 128)``
flattens to the caller's ``(length,)``.

Runs in ``interpret=True`` mode on non-TPU platforms (tests run on the CPU mesh); the caller
(``ops.histogram.bincount``) falls back to the XLA one-hot/segment-sum path if this kernel
raises.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

_LANES = 128
_ROWS = 32  # samples tile = (32, 128) = 4096 indices per grid step


def _bincount_kernel(idx_ref, out_ref):
    bin_block = pl.program_id(0)
    sample_step = pl.program_id(1)

    @pl.when(sample_step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    idx = idx_ref[...]  # (ROWS, LANES) int32
    # output tile is (8, LANES): 8 sublane rows of 128 bins each. Accumulate in int32 so counts
    # stay exact past 2^24 per bin (the float32 mantissa cap the XLA one-hot path is subject to).
    for r in range(8):
        bins = (bin_block * 8 + r) * _LANES + jax.lax.broadcasted_iota(jnp.int32, (1, _LANES), 1)
        eq = (idx[:, :, None] == bins[None, :, :]).astype(jnp.int32)  # (ROWS, LANES, LANES)
        out_ref[r, :] += jnp.sum(eq, axis=(0, 1))


@functools.partial(jax.jit, static_argnames=("length", "interpret"))
def _bincount_pallas_impl(idx_padded: Array, length: int, interpret: bool) -> Array:
    n = idx_padded.shape[0]
    num_sample_blocks = n // (_ROWS * _LANES)
    num_bin_blocks = (length + 8 * _LANES - 1) // (8 * _LANES)
    # sample dim INNERMOST: the output block then stays resident in VMEM across all of its
    # accumulation steps (Pallas only defines revisiting for consecutive grid steps)
    out = pl.pallas_call(
        _bincount_kernel,
        grid=(num_bin_blocks, num_sample_blocks),
        in_specs=[pl.BlockSpec((_ROWS, _LANES), lambda b, s: (s, 0))],
        out_specs=pl.BlockSpec((8, _LANES), lambda b, s: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((num_bin_blocks * 8, _LANES), jnp.int32),
        interpret=interpret,
    )(idx_padded.reshape(num_sample_blocks * _ROWS, _LANES))
    return out.reshape(-1)[:length]


def bincount_pallas(x: Array, length: int) -> Array:
    """Counts of int32 values in ``[0, length)``; out-of-range values are dropped.

    Same contract as ``ops.histogram.bincount`` (mask, never drop: out-of-range indices match
    no bin). Pads the input to a full tile with an out-of-range sentinel.
    """
    x = jnp.asarray(x).reshape(-1)
    # remap out-of-range values BEFORE the int32 cast (an int64 value could otherwise wrap into
    # a valid bin); the sentinel sits past `length`, inside the kernel's padded bin range, and
    # is discarded by the final [:length] slice
    block = _ROWS * _LANES
    n_pad = max(((x.size + block - 1) // block) * block, block)
    sentinel = jnp.asarray(length, jnp.int32)
    x32 = jnp.where((x >= 0) & (x < length), x, length).astype(jnp.int32)
    padded = jnp.full((n_pad,), sentinel, jnp.int32).at[: x.size].set(x32)
    interpret = jax.default_backend() != "tpu"
    return _bincount_pallas_impl(padded, length, interpret)
