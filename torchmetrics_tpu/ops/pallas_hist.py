"""Pallas bincount kernel (SURVEY §2.9: the named Pallas candidate — XLA's native lowering of
bincount is either a scatter-add (non-deterministic on some backends, serialised on TPU) or a
materialised one-hot).

Design: grid over (sample blocks × bin rows). Each step loads a ``(ROWS, 128)`` tile of indices
into VMEM, compares it against one 128-wide row of bin ids with a broadcasted iota — pure VPU
work, no HBM one-hot — and accumulates the 128 partial counts into the output tile, revisiting
the same output block across the sample-grid dimension. Counts layout ``(num_bin_rows, 128)``
flattens to the caller's ``(length,)``.

Runs in ``interpret=True`` mode on non-TPU platforms (tests run on the CPU mesh); the caller
(``ops.histogram.bincount``) falls back to the XLA one-hot/segment-sum path if this kernel
raises.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

_LANES = 128
_ROWS = 32  # samples tile = (32, 128) = 4096 indices per grid step


def _bincount_kernel(idx_ref, out_ref):
    bin_block = pl.program_id(0)
    sample_step = pl.program_id(1)

    @pl.when(sample_step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    idx = idx_ref[...]  # (ROWS, LANES) int32
    # output tile is (8, LANES): 8 sublane rows of 128 bins each. Accumulate in int32 so counts
    # stay exact past 2^24 per bin (the float32 mantissa cap the XLA one-hot path is subject to).
    for r in range(8):
        bins = (bin_block * 8 + r) * _LANES + jax.lax.broadcasted_iota(jnp.int32, (1, _LANES), 1)
        eq = (idx[:, :, None] == bins[None, :, :]).astype(jnp.int32)  # (ROWS, LANES, LANES)
        out_ref[r, :] += jnp.sum(eq, axis=(0, 1))


@functools.partial(jax.jit, static_argnames=("length", "interpret"))
def _bincount_pallas_impl(idx_padded: Array, length: int, interpret: bool) -> Array:
    n = idx_padded.shape[0]
    num_sample_blocks = n // (_ROWS * _LANES)
    num_bin_blocks = (length + 8 * _LANES - 1) // (8 * _LANES)
    # sample dim INNERMOST: the output block then stays resident in VMEM across all of its
    # accumulation steps (Pallas only defines revisiting for consecutive grid steps)
    out = pl.pallas_call(
        _bincount_kernel,
        grid=(num_bin_blocks, num_sample_blocks),
        in_specs=[pl.BlockSpec((_ROWS, _LANES), lambda b, s: (s, 0))],
        out_specs=pl.BlockSpec((8, _LANES), lambda b, s: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((num_bin_blocks * 8, _LANES), jnp.int32),
        interpret=interpret,
    )(idx_padded.reshape(num_sample_blocks * _ROWS, _LANES))
    return out.reshape(-1)[:length]


def bincount_pallas(x: Array, length: int) -> Array:
    """Counts of int32 values in ``[0, length)``; out-of-range values are dropped.

    Same contract as ``ops.histogram.bincount`` (mask, never drop: out-of-range indices match
    no bin). Pads the input to a full tile with an out-of-range sentinel.
    """
    x = jnp.asarray(x).reshape(-1)
    # remap out-of-range values BEFORE the int32 cast (an int64 value could otherwise wrap into
    # a valid bin); the sentinel sits past `length`, inside the kernel's padded bin range, and
    # is discarded by the final [:length] slice
    block = _ROWS * _LANES
    n_pad = max(((x.size + block - 1) // block) * block, block)
    sentinel = jnp.asarray(length, jnp.int32)
    x32 = jnp.where((x >= 0) & (x < length), x, length).astype(jnp.int32)
    padded = jnp.full((n_pad,), sentinel, jnp.int32).at[: x.size].set(x32)
    interpret = jax.default_backend() != "tpu"
    return _bincount_pallas_impl(padded, length, interpret)


# ---------------------------------------------------------------------------
# Weighted histogram-pair kernel (sketch subsystem, docs/sketches.md)
# ---------------------------------------------------------------------------
# The streaming curve sketch folds every batch into a (pos, neg) weighted histogram pair.
# XLA's lowering is either a serialised scatter-add or a materialised (N, bins) one-hot;
# this kernel is the fused scatter-add twin of the bincount kernel above: both weight
# streams accumulate against the same in-register index compare, so the batch is read
# once and the (N, bins) indicator never exists in VMEM or HBM.


def _hist_pair_kernel(idx_ref, wp_ref, wn_ref, out_ref):
    bin_block = pl.program_id(0)
    sample_step = pl.program_id(1)

    @pl.when(sample_step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    idx = idx_ref[...]  # (ROWS, LANES) int32
    wp = wp_ref[...]  # (ROWS, LANES) f32
    wn = wn_ref[...]
    # output tile (16, LANES): rows 0..7 = positive mass, rows 8..15 = negative mass for
    # the 8 sublane bin rows of this block. One compare feeds both accumulations.
    for r in range(8):
        bins = (bin_block * 8 + r) * _LANES + jax.lax.broadcasted_iota(jnp.int32, (1, _LANES), 1)
        eq = (idx[:, :, None] == bins[None, :, :]).astype(jnp.float32)  # (ROWS, LANES, LANES)
        out_ref[r, :] += jnp.sum(wp[:, :, None] * eq, axis=(0, 1))
        out_ref[8 + r, :] += jnp.sum(wn[:, :, None] * eq, axis=(0, 1))


@functools.partial(jax.jit, static_argnames=("length", "interpret"))
def _hist_pair_pallas_impl(
    idx_padded: Array, wp_padded: Array, wn_padded: Array, length: int, interpret: bool
) -> Array:
    n = idx_padded.shape[0]
    num_sample_blocks = n // (_ROWS * _LANES)
    num_bin_blocks = (length + 8 * _LANES - 1) // (8 * _LANES)
    shaped = lambda x: x.reshape(num_sample_blocks * _ROWS, _LANES)
    # sample dim INNERMOST, exactly like the bincount kernel: the output block stays
    # resident in VMEM across all of its accumulation steps
    out = pl.pallas_call(
        _hist_pair_kernel,
        grid=(num_bin_blocks, num_sample_blocks),
        in_specs=[
            pl.BlockSpec((_ROWS, _LANES), lambda b, s: (s, 0)),
            pl.BlockSpec((_ROWS, _LANES), lambda b, s: (s, 0)),
            pl.BlockSpec((_ROWS, _LANES), lambda b, s: (s, 0)),
        ],
        out_specs=pl.BlockSpec((16, _LANES), lambda b, s: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((num_bin_blocks * 16, _LANES), jnp.float32),
        interpret=interpret,
    )(shaped(idx_padded), shaped(wp_padded), shaped(wn_padded))
    # (blocks, [pos|neg], 8, LANES) -> (2, blocks*8*LANES) -> slice the padded bin tail
    out = out.reshape(num_bin_blocks, 2, 8 * _LANES).transpose(1, 0, 2).reshape(2, -1)
    return out[:, :length]


def hist_pair_pallas(idx: Array, pos_w: Array, neg_w: Array, length: int) -> Array:
    """``(2, length)`` weighted counts of ``idx`` under two weight streams, one pass.

    Same masking contract as :func:`bincount_pallas` (out-of-range indices are remapped
    to a sentinel bin that the final slice drops); samples are padded to a full tile with
    zero weights. f32 accumulation — exact to 2^24 unit weights per (stream, bin).
    """
    idx = jnp.asarray(idx).reshape(-1)
    pos_w = jnp.asarray(pos_w, jnp.float32).reshape(-1)
    neg_w = jnp.asarray(neg_w, jnp.float32).reshape(-1)
    block = _ROWS * _LANES
    n_pad = max(((idx.size + block - 1) // block) * block, block)
    idx32 = jnp.where((idx >= 0) & (idx < length), idx, length).astype(jnp.int32)

    def pad(x, fill, dtype):
        return jnp.full((n_pad,), fill, dtype).at[: x.size].set(x)

    interpret = jax.default_backend() != "tpu"
    return _hist_pair_pallas_impl(
        pad(idx32, length, jnp.int32),
        pad(pos_w, 0.0, jnp.float32),
        pad(neg_w, 0.0, jnp.float32),
        length,
        interpret,
    )
