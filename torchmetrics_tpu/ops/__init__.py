"""TPU-native compute kernels shared by the functional layer.

Where the reference relies on ``torch.bincount`` with an arange+eq fallback for XLA backends
(``src/torchmetrics/utilities/data.py:169-199``), these kernels are designed for XLA from the
start:

- ``bincount`` / ``confusion_matrix_update``: lowered as one-hot matmuls that run on the MXU
  (systolic array) for small cardinalities — a (N, C) one-hot against ones / another one-hot is a
  single dense matmul, the highest-throughput op on TPU — with a segment-sum scatter path for
  large cardinalities where the one-hot would blow HBM.
- ``segment_*``: sorted-segment reductions that replace the reference's per-query Python loops
  (e.g. retrieval, ``src/torchmetrics/retrieval/base.py:165-182``).

"""
from torchmetrics_tpu.ops.histogram import bincount, bincount_weighted, confusion_matrix_update
from torchmetrics_tpu.ops.segments import (
    segment_count,
    segment_max,
    segment_mean,
    segment_mean_pair,
    segment_min,
    segment_sum,
)

__all__ = [
    "bincount",
    "bincount_weighted",
    "confusion_matrix_update",
    "segment_sum",
    "segment_count",
    "segment_mean",
    "segment_mean_pair",
    "segment_max",
    "segment_min",
]
