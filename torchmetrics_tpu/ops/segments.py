"""Sorted-segment reductions.

These replace the reference's per-query Python loops (retrieval metrics iterate groups on the
host, ``src/torchmetrics/retrieval/base.py:165-182``) with single fused XLA reductions over a
statically-shaped segment-id vector — the idiomatic TPU formulation of "group-by + reduce".

The same primitives carry the keyed multi-tenant engine (``torchmetrics_tpu.keyed``): a
mixed-tenant batch routes into a ``[num_keys, ...]`` state table through one segment
reduction per state instead of one dispatch per tenant. The keyed ``MeanMetric`` needs the
(sums, counts) PAIR as state — the ratio is only formed at ``compute()`` — which is what
:func:`segment_mean_pair` exists for.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import Array


def segment_sum(data: Array, segment_ids: Array, num_segments: int) -> Array:
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_count(segment_ids: Array, num_segments: int, dtype=jnp.int32) -> Array:
    """Number of elements per segment (empty segments count 0)."""
    return jax.ops.segment_sum(
        jnp.ones(jnp.shape(segment_ids), dtype), segment_ids, num_segments=num_segments
    )


def segment_mean_pair(data: Array, segment_ids: Array, num_segments: int) -> Tuple[Array, Array]:
    """Per-segment ``(sums, counts)`` — the mergeable pair, NOT the ratio.

    Mean-shaped accumulator states must hold the pair: two pairs merge by elementwise
    addition (associative, cross-batch and cross-process), while two ratios merge as
    nothing. Counts follow ``data``'s dtype so the pair stays homogeneous with the sums.
    """
    sums = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    counts = jax.ops.segment_sum(jnp.ones_like(data), segment_ids, num_segments=num_segments)
    return sums, counts


def segment_mean(data: Array, segment_ids: Array, num_segments: int) -> Array:
    sums, counts = segment_mean_pair(data, segment_ids, num_segments)
    return sums / jnp.maximum(counts, jnp.ones((), counts.dtype))


def segment_max(data: Array, segment_ids: Array, num_segments: int) -> Array:
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


def segment_min(data: Array, segment_ids: Array, num_segments: int) -> Array:
    return jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
