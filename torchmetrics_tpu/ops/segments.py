"""Sorted-segment reductions.

These replace the reference's per-query Python loops (retrieval metrics iterate groups on the
host, ``src/torchmetrics/retrieval/base.py:165-182``) with single fused XLA reductions over a
statically-shaped segment-id vector — the idiomatic TPU formulation of "group-by + reduce".
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array


def segment_sum(data: Array, segment_ids: Array, num_segments: int) -> Array:
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_mean(data: Array, segment_ids: Array, num_segments: int) -> Array:
    sums = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    counts = jax.ops.segment_sum(jnp.ones_like(data, dtype=jnp.float32), segment_ids, num_segments=num_segments)
    return sums / jnp.maximum(counts, 1.0)


def segment_max(data: Array, segment_ids: Array, num_segments: int) -> Array:
    return jax.ops.segment_max(data, segment_ids, num_segments=num_segments)


def segment_min(data: Array, segment_ids: Array, num_segments: int) -> Array:
    return jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
