"""Zero-overhead per-step dispatch: AOT executables, buffer donation, deferred accumulation.

The per-step ``forward`` protocol — the shape every real training loop uses — pays host-side
costs the fused sweep never sees: jit's per-call argument processing (pytree flattening,
signature hashing, cache lookup), dict rebuilds of the state, and a fresh set of output
buffers every step. BENCH r01–r05 put the fused sweep at 16.8x the torch-CPU reference but
per-step ``forward`` at only 2.1x; the gap is pure dispatch overhead. This module is the
host-side machinery that closes it, in three tiers (see ``docs/performance.md``):

- **AOT executables** (:func:`aot_compile`, :class:`FastStepCache`): the fused step program is
  lowered and compiled ONCE per abstract input signature via ``jax.jit(...).lower(...)
  .compile()`` and dispatched through the compiled executable with pre-flattened positional
  leaves — steady-state steps skip jit's argument-processing path entirely. Dict/kwarg
  arguments are deliberately excluded from the executable's calling convention: flat
  positional leaves are the only layout whose ``Compiled.__call__`` cost matches the jit
  C++ fast path (measured ~3x slower for dict-shaped args).
- **Buffer donation**: the global state tensors are donated into the merged output
  (``donate_argnums``) so each step reuses device buffers instead of allocating. Donated
  buffers are DELETED — the engine guards this with a state-generation counter and an
  in-flight flag on ``StateStore`` (reads mid-dispatch raise cleanly), copy-on-alias for
  default tensors, and a shared-state gate for compute-group members (jaxlint rule TPU007
  is the static twin: reading a donated buffer after dispatch). Donation composes with
  sharded state (``Metric.shard``, docs/distributed.md): the AOT example inputs carry the
  states' ``NamedSharding`` and the kernels are closed under matching sharding
  constraints, so the executable aliases donated buffers shard-for-shard — mesh layout
  AND buffer reuse survive every step.
- **Deferred accumulation** (:class:`BufferedUpdater`, via ``Metric.buffered(k)`` /
  ``MetricCollection.buffered(k)``): stacks up to ``k`` update batches host-side and flushes
  them through the existing ``update_scan`` program in one launch — k dispatches become one
  (plus the stack) for update-only loops.

Telemetry (always-on counters in the global ``obs`` registry): ``dispatch.aot_compiles``,
``dispatch.aot_cache_hits``, ``dispatch.aot_fallbacks``, ``dispatch.donated_steps``,
``dispatch.buffered_flushes``; the per-step host-overhead timer ``dispatch.host_overhead``
records (while tracing is enabled) the wall time a fast step spends OUTSIDE the compiled
executable.

Opt-outs: ``TM_TPU_FAST_DISPATCH=0`` disables the AOT tier (jit paths remain),
``TM_TPU_DONATION=0`` keeps AOT but disables donation.

Threading contract (the async serving tier, ``torchmetrics_tpu.serve``): nothing in this
module takes locks — ``FastStepCache``, ``dispatch_step`` and ``commit_step`` assume a
SINGLE mutator at a time. The ingestion engine honors that by construction: its drain
thread is the only caller while the in-flight window is non-empty (every user-thread
access path quiesces the window first), so the drain rides these seams exactly like a
single-threaded training loop — donation, generation counting, and the AOT caches need
no additional synchronization.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

from torchmetrics_tpu.obs import telemetry
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError

ENV_FAST_DISPATCH = "TM_TPU_FAST_DISPATCH"
ENV_DONATION = "TM_TPU_DONATION"
_FALSY = frozenset(
    v
    for base in ("0", "false", "no", "off")
    for v in (base, base.upper(), base.capitalize())
)


def fast_dispatch_enabled() -> bool:
    """AOT fast dispatch is opt-out: on unless ``TM_TPU_FAST_DISPATCH`` is falsy.

    Deliberately one dict lookup — this runs once per forward step.
    """
    return os.environ.get(ENV_FAST_DISPATCH, "1") not in _FALSY


def donation_enabled() -> bool:
    """Buffer donation is opt-out: on unless ``TM_TPU_DONATION`` is falsy."""
    return os.environ.get(ENV_DONATION, "1") not in _FALSY


def leaf_signature(leaves: List[Any]) -> Tuple:
    """Hashable abstract signature of a flat leaf list (shape, dtype, weak-type per leaf).

    Only computed on the SLOW path (first call per shape, or after an aval mismatch);
    steady-state steps never pay for it — they key on the pytree structure alone and let
    the executable's own aval check catch shape drift. Dtype objects are kept raw
    (``np.dtype`` hashes fast; ``str(dtype)`` measured ~10x slower per leaf).
    """
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            # non-array leaf (str/None/object): not AOT-compilable — poison the signature
            # with the value's type so the builder fails fast and the caller falls back
            sig.append((type(leaf).__name__,))
            continue
        sig.append((shape, dtype, bool(getattr(leaf, "weak_type", False))))
    return tuple(sig)


def _cpp_call(compiled: Any) -> Callable:
    """The executable's cached C++ fast call — what ``Compiled.__call__`` builds lazily on
    its first invocation, resolved eagerly so steady-state steps skip the lazy-init check
    and one Python frame. Falls back to the ``Compiled`` object itself (same semantics)."""
    try:
        call = compiled._executable.create_cpp_call(
            compiled._no_kwargs, compiled.in_tree, compiled.out_tree
        )
        return call if call is not None else compiled
    except Exception:  # pragma: no cover - private-API drift: __call__ still works
        return compiled


def aot_compile(
    fn: Callable,
    example_args: Tuple,
    donate_argnums: Tuple[int, ...] = (),
    owner: Any = None,
    kind: Optional[str] = None,
):
    """``jax.jit(fn).lower(*example).compile()`` with the compile counted in telemetry.

    Returns the ``Compiled`` executable. ``example_args`` are concrete arrays (or
    ``ShapeDtypeStruct``s) fixing the abstract signature; donation is declared here so the
    executable aliases the donated inputs into its outputs. When ``owner``/``kind`` name
    the metric and kernel, the executable's XLA cost/memory analysis is captured into the
    process-global cost ledger (``obs.cost_ledger()``) — the AOT tier's profiler seam,
    paid once per compile and never on the step path.
    """
    import time

    import jax

    t0 = time.perf_counter()
    lowered = jax.jit(fn, donate_argnums=donate_argnums).lower(*example_args)
    compiled = lowered.compile()
    compile_us = (time.perf_counter() - t0) * 1e6
    telemetry.counter("dispatch.aot_compiles").inc()
    if owner is not None and kind is not None:
        from torchmetrics_tpu.obs import profiler as _profiler

        try:
            signature = _profiler.abstract_signature(example_args)
            _profiler.record_compiled(type(owner).__name__, kind, "aot", signature, compiled)
        except Exception:  # pragma: no cover - profiling must never break a compile
            signature = None
        # compile-plane ledger row: wall time, StableHLO fingerprint, cost deltas
        # (docs/observability.md "Compile plane")
        try:
            from torchmetrics_tpu.obs import xplane as _xplane

            _xplane.note_aot_compile(
                owner, kind, signature or "", lowered, compiled, compile_us
            )
        except Exception:  # pragma: no cover - the ledger must never break a compile
            pass
    return compiled


class AotEntry:
    """One compiled step executable plus the layout facts needed to call it flat."""

    __slots__ = ("compiled", "call", "state_names", "donated")

    def __init__(self, compiled: Any, state_names: Tuple[str, ...], donated: bool) -> None:
        self.compiled = compiled
        self.call = _cpp_call(compiled)
        self.state_names = state_names
        self.donated = donated


class FastStepCache:
    """Cache of AOT entries: structure-keyed fast path, signature-keyed slow path.

    Per-step loops have stable shapes, so the hot path checks only the input pytree
    structure (treedef equality, one C comparison) and dispatches the last entry — the
    executable's own aval check is the shape guard. A mismatch (new batch shape, weak→
    strong dtype flip after the first merge) drops to the signature-keyed dict and
    compiles at most once per distinct signature. ``broken`` latches True after a build
    failure so a non-compilable workload pays the probe exactly once and then stays on
    the jit path.
    """

    __slots__ = ("entries", "_last_treedef", "_last_entry", "broken", "donate")

    def __init__(self, donate: bool = False) -> None:
        self.entries: Dict[Any, AotEntry] = {}
        self._last_treedef: Any = None
        self._last_entry: Optional[AotEntry] = None
        self.broken = False
        #: donation policy the entries were built under; the owner drops the cache when its
        #: policy flips (e.g. a metric's state becomes compute-group shared after formation)
        self.donate = donate

    def fast_entry(self, treedef: Any) -> Optional[AotEntry]:
        """The last-dispatched entry, iff the input structure matches (hot path)."""
        # PyTreeDef.__eq__ rejects non-PyTreeDef operands, so the None check comes first
        if self._last_entry is not None and treedef == self._last_treedef:
            return self._last_entry
        return None

    def keyed_entry(self, key: Any) -> Optional[AotEntry]:
        return self.entries.get(key)

    def store(self, key: Any, treedef: Any, entry: AotEntry) -> None:
        self.entries[key] = entry
        self._last_treedef, self._last_entry = treedef, entry

    def promote(self, treedef: Any, entry: AotEntry) -> None:
        self._last_treedef, self._last_entry = treedef, entry

    def mark_broken(self) -> None:
        self.broken = True
        telemetry.counter("dispatch.aot_fallbacks").inc()


def dispatch_step(  # jaxlint: donates(2) — state_leaves die with the executable call
    cache: FastStepCache,
    builder: Callable[[List[Any], Any], AotEntry],
    state_leaves: List[Any],
    prefix: Tuple,
    leaves: List[Any],
    treedef: Any,
) -> Tuple[AotEntry, Any]:
    """Dispatch one fused step through the fastest matching executable.

    Hot path: treedef check + one C++ executable call — no Python-side signature
    hashing, no jit argument processing. An aval mismatch from the executable (shape
    change, dtype flip) is caught ONLY if the state buffers are still alive (the aval
    check runs before donation; a post-donation failure must propagate to the caller's
    recovery) and resolved through the signature-keyed slow path, compiling on miss.
    """
    entry = cache.fast_entry(treedef)
    if entry is not None:
        try:
            out = entry.call(*state_leaves, *prefix, *leaves)
            telemetry.counter("dispatch.aot_cache_hits").inc()
            return entry, out
        except Exception:
            if any(
                getattr(leaf, "is_deleted", _never)() for leaf in state_leaves
            ):  # donated and dead: not a shape miss — the caller must recover
                raise
    key = (treedef, leaf_signature(state_leaves), leaf_signature(leaves))
    entry = cache.keyed_entry(key)
    if entry is None:
        entry = builder(leaves, treedef)
        cache.store(key, treedef, entry)
    else:
        telemetry.counter("dispatch.aot_cache_hits").inc()
        cache.promote(treedef, entry)
    return entry, entry.call(*state_leaves, *prefix, *leaves)


def _never() -> bool:
    return False


def commit_step(state: Any, entry: AotEntry, out: Any) -> None:  # jaxlint: donation-commit
    """Install a dispatched step's state outputs into a ``StateStore``.

    Donated entries commit through the store's generation machinery (the old buffers are
    gone — XLA aliased them into ``out``); non-donated entries are plain dict swaps. One
    implementation for every fast tier (forward step, update scan, single update, keyed).
    """
    if entry.donated:
        state.commit_donated(entry.state_names, out)
        telemetry.counter("dispatch.donated_steps").inc()
    else:
        for name, arr in zip(entry.state_names, out):
            state.tensors[name] = arr
        state.abort_donated()


def recover_failed_step(metric: Any, state: Any, kind: str) -> None:  # jaxlint: donation-commit
    """Post-exception cleanup shared by the fast dispatch tiers.

    Clears the in-flight latch, and — when the dispatch died AFTER donating (the old
    buffers are deleted and nothing replaced them) — restores the registered defaults so
    the metric stays usable, with a rank-zero warning naming the failed ``kind``.
    """
    state.abort_donated()
    if any(getattr(leaf, "is_deleted", _never)() for leaf in state.tensors.values()):
        for name in state.tensors:
            state.tensors[name] = metric._defaults[name]
        from torchmetrics_tpu.utils.prints import rank_zero_warn

        rank_zero_warn(
            f"A donated {kind} dispatch of {type(metric).__name__} failed mid-flight;"
            " the metric state was reset to defaults.",
            UserWarning,
        )


def graph_squeeze(value: Any) -> Any:
    """Trace-time twin of ``Metric._squeeze_if_scalar``: fold the shape-(1,) squeeze into
    the compiled program so the host never pays an eager squeeze dispatch per step."""
    import jax.numpy as jnp

    if getattr(value, "shape", None) == (1,):
        return jnp.squeeze(value)
    return value


def _batch_key(args: tuple, kwargs: dict) -> Tuple:
    """Cheap structural key of one buffered batch: arity, kwarg names, leaf shapes/dtypes."""
    return (
        tuple((getattr(a, "shape", None), str(getattr(a, "dtype", ""))) for a in args),
        tuple(sorted((k, getattr(v, "shape", None), str(getattr(v, "dtype", ""))) for k, v in kwargs.items())),
    )


class BufferedUpdater:
    """Deferred micro-batch accumulator: stack up to ``k`` batches, flush in one launch.

    Returned by ``Metric.buffered(k)`` / ``MetricCollection.buffered(k)``. ``update``
    appends host-side (no dispatch); when ``k`` batches are pending — or on
    :meth:`flush` / :meth:`compute` / context exit — the stack is folded through the
    target's ``update_batches`` (the compiled ``update_scan`` program) in one launch.

    While batches are pending, the target's state is stale mid-flight: the wrapped
    metrics guard direct ``update``/``forward``/``compute``/``metric_state`` access with
    a clean :class:`TorchMetricsUserError` until the buffer flushes. A shape/structure
    change between buffered batches flushes the pending stack first (stacking requires
    uniform shapes), so ragged tails degrade gracefully instead of erroring.

    ``journal`` is the robustness layer's write-ahead seam: when set (any object with an
    ``append(args, kwargs)`` method — canonically
    :class:`torchmetrics_tpu.robust.journal.Journal`), each batch is journaled durably at
    ``update`` time, BEFORE it enters the host-side window. A preemption that strikes
    with batches pending therefore loses nothing: recovery replays the journaled stream,
    including the un-flushed window (docs/robustness.md).
    """

    def __init__(self, target: Any, k: int, journal: Optional[Any] = None) -> None:
        if int(k) < 1:
            raise ValueError(f"buffered(k) needs k >= 1, got {k}")
        self._target = target
        self._k = int(k)
        self._journal = journal
        self._pending: List[Tuple[tuple, dict]] = []
        self._pending_key: Optional[Tuple] = None

    # ------------------------------------------------------------------ target plumbing
    def _metrics(self) -> List[Any]:
        values = getattr(self._target, "values", None)
        if callable(values):  # MetricCollection
            return list(self._target.values(copy_state=False))
        return [self._target]

    def _set_pending(self, n: int) -> None:
        for m in self._metrics():
            object.__setattr__(m, "_buffered_pending", n)

    # -------------------------------------------------------------------------- protocol
    @property
    def pending(self) -> int:
        """Number of batches buffered and not yet flushed."""
        return len(self._pending)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Buffer one batch; flushes automatically when ``k`` batches are pending."""
        if self._journal is not None:
            # write-ahead: the batch is durable before it is merely pending in memory
            self._journal.append(args, kwargs)
        key = _batch_key(args, kwargs)
        if self._pending and key != self._pending_key:
            # ragged tail: stacking requires uniform shapes, so the pending window is
            # folded early — a tier decision worth explaining (it costs one extra launch)
            try:
                from torchmetrics_tpu.obs import xplane as _xplane

                for m in self._metrics():
                    _xplane.note_decision(m, "buffered", "update_scan", "ragged_buffered_flush")
            except Exception:  # pragma: no cover - explain notes must never break a flush
                pass
            self.flush()
        self._pending_key = key
        self._pending.append((args, kwargs))
        self._set_pending(len(self._pending))
        if len(self._pending) >= self._k:
            self.flush()

    def flush(self) -> None:
        """Fold every pending batch into the target state with one scan launch."""
        if not self._pending:
            return
        import jax.numpy as jnp

        batches = self._pending
        self._pending = []
        self._pending_key = None
        self._set_pending(0)
        if len(batches) == 1:
            args, kwargs = batches[0]
            self._target.update(*args, **kwargs)
        else:
            first_args, first_kwargs = batches[0]
            stacked_args = tuple(
                jnp.stack([b[0][i] for b in batches]) for i in range(len(first_args))
            )
            stacked_kwargs = {
                name: jnp.stack([b[1][name] for b in batches]) for name in first_kwargs
            }
            self._target.update_batches(*stacked_args, **stacked_kwargs)
        telemetry.counter("dispatch.buffered_flushes").inc()

    def compute(self) -> Any:
        """Flush pending batches, then compute the target."""
        self.flush()
        return self._target.compute()

    def reset(self) -> None:
        """Drop pending batches and reset the target."""
        self._discard()
        self._target.reset()

    def _discard(self) -> int:
        """Drop pending batches and disarm the stale-state guard; returns the drop count."""
        n = len(self._pending)
        self._pending.clear()
        self._pending_key = None
        self._set_pending(0)
        if n:
            telemetry.counter("dispatch.buffered_discards").inc(n)
        return n

    def __enter__(self) -> "BufferedUpdater":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        """Flush on clean exit; discard-and-warn on error exit.

        Either way the pending guard is DISARMED before control leaves the block — an
        exception (from the loop body, or from the flush itself) must never leave the
        metric latched unusable behind the buffered-pending guard.
        """
        if exc_type is None:
            try:
                self.flush()
            except BaseException:
                self._discard()  # a failed flush must not leave the guard armed
                raise
            return False
        dropped = self._discard()  # an erroring loop must not flush half a window into the state
        if dropped:
            from torchmetrics_tpu.utils.prints import rank_zero_warn

            rank_zero_warn(
                f"BufferedUpdater context exited with {exc_type.__name__}: discarded"
                f" {dropped} pending batch(es). The metric state holds only the batches"
                " flushed before the error; the metric remains usable.",
                UserWarning,
            )
        return False

    def __len__(self) -> int:
        return len(self._pending)


def guard_buffered_pending(metric: Any, op: str) -> None:
    """Raise cleanly when ``metric`` is touched while a BufferedUpdater holds its batches."""
    pending = metric.__dict__.get("_buffered_pending", 0)
    if pending:
        raise TorchMetricsUserError(
            f"Cannot run {op!r} on {type(metric).__name__}: {pending} batch(es) are pending"
            " in a buffered accumulator, so the metric state is stale mid-flight. Call"
            " flush() on the buffer (or use its compute(), which flushes first)."
        )
