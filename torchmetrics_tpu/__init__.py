"""torchmetrics_tpu: a TPU-native (JAX/XLA/Pallas) metrics framework.

Re-design of TorchMetrics (reference: oguz-hanoglu/torchmetrics) for TPU hardware: metric state
lives as pytrees of ``jax.Array`` in HBM, updates/computes are jit-compiled XLA kernels, and
distributed sync is mesh collectives over ICI/DCN. See SURVEY.md for the blueprint.
"""
from torchmetrics_tpu.__about__ import __version__
from torchmetrics_tpu.aggregation import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric

__all__ = [
    "__version__",
    "Metric",
    "MetricCollection",
    "CatMetric",
    "MaxMetric",
    "MeanMetric",
    "MinMetric",
    "SumMetric",
]
