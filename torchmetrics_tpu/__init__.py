"""torchmetrics_tpu: a TPU-native (JAX/XLA/Pallas) metrics framework.

Re-design of TorchMetrics (reference: oguz-hanoglu/torchmetrics) for TPU hardware: metric state
lives as pytrees of ``jax.Array`` in HBM, updates/computes are jit-compiled XLA kernels, and
distributed sync is mesh collectives over ICI/DCN. See SURVEY.md for the blueprint.

Top-level surface mirrors the reference's ``torchmetrics.__all__``
(``src/torchmetrics/__init__.py:150``, 101 symbols) as domains land.
"""
from torchmetrics_tpu.__about__ import __version__
from torchmetrics_tpu import functional
from torchmetrics_tpu.aggregation import (
    CatMetric,
    MaxMetric,
    MeanMetric,
    MinMetric,
    SumMetric,
)
from torchmetrics_tpu.classification import (
    AUROC,
    ROC,
    Accuracy,
    AveragePrecision,
    CalibrationError,
    CohenKappa,
    ConfusionMatrix,
    Dice,
    ExactMatch,
    F1Score,
    FBetaScore,
    HammingDistance,
    HingeLoss,
    JaccardIndex,
    MatthewsCorrCoef,
    Precision,
    PrecisionAtFixedRecall,
    PrecisionRecallCurve,
    Recall,
    RecallAtFixedPrecision,
    Specificity,
    SpecificityAtSensitivity,
    StatScores,
)
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.retrieval import (
    RetrievalFallOut,
    RetrievalHitRate,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalPrecisionRecallCurve,
    RetrievalRecall,
    RetrievalRecallAtFixedPrecision,
    RetrievalRPrecision,
)
from torchmetrics_tpu.regression import (
    ConcordanceCorrCoef,
    CosineSimilarity,
    ExplainedVariance,
    KLDivergence,
    KendallRankCorrCoef,
    LogCoshError,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    MinkowskiDistance,
    PearsonCorrCoef,
    R2Score,
    RelativeSquaredError,
    SpearmanCorrCoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
    WeightedMeanAbsolutePercentageError,
)

__all__ = [
    "__version__",
    "functional",
    "Metric",
    "MetricCollection",
    # aggregation
    "CatMetric",
    "MaxMetric",
    "MeanMetric",
    "MinMetric",
    "SumMetric",
    # classification
    "AUROC",
    "Accuracy",
    "AveragePrecision",
    "CalibrationError",
    "CohenKappa",
    "ConfusionMatrix",
    "Dice",
    "ExactMatch",
    "F1Score",
    "FBetaScore",
    "HammingDistance",
    "HingeLoss",
    "JaccardIndex",
    "MatthewsCorrCoef",
    "Precision",
    "PrecisionAtFixedRecall",
    "PrecisionRecallCurve",
    "ROC",
    "Recall",
    "RecallAtFixedPrecision",
    "Specificity",
    "SpecificityAtSensitivity",
    "StatScores",
    # retrieval
    "RetrievalFallOut",
    "RetrievalHitRate",
    "RetrievalMAP",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalPrecisionRecallCurve",
    "RetrievalRecall",
    "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision",
    # regression
    "ConcordanceCorrCoef",
    "CosineSimilarity",
    "ExplainedVariance",
    "KLDivergence",
    "KendallRankCorrCoef",
    "LogCoshError",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "MinkowskiDistance",
    "PearsonCorrCoef",
    "R2Score",
    "RelativeSquaredError",
    "SpearmanCorrCoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "WeightedMeanAbsolutePercentageError",
]
