"""Stateful text metrics (reference ``src/torchmetrics/text/*.py``).

String inputs cannot be traced, so text metric updates run the host counting path and fold
results into fixed-shape device states (``jit_update=False``); computes are trace-safe jnp.
State layouts follow the reference: BLEU keeps (n_gram,) count vectors (``text/bleu.py:91-94``),
the error-rate family keeps 2-4 sum scalars (``text/wer.py:82-83``), chrF keeps six per-order
vectors (vs the reference's dicts of scalars, ``text/chrf.py:131-146``).
"""
from __future__ import annotations

from typing import Any, Dict, Literal, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.text._edit import edit_distance_batch
from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update_batched, _tokenize_fn
from torchmetrics_tpu.functional.text.chrf import (
    _chrf_score_compute,
    _chrf_score_update_batched,
    _validate_chrf_args,
)
from torchmetrics_tpu.functional.text.edit import _edit_distance_compute, _edit_distance_update
from torchmetrics_tpu.functional.text.perplexity import _perplexity_compute, _perplexity_update
from torchmetrics_tpu.utils.prints import rank_zero_warn
from torchmetrics_tpu.functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from torchmetrics_tpu.functional.text.squad import _squad_compute, _squad_input_check, _squad_update
from torchmetrics_tpu.functional.text.wer import (
    _cer_update,
    _mer_update,
    _wer_update,
    _word_info_update,
    _wip_compute,
    _word_info_lost_compute,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError


class _HostTextMetric(Metric):
    """Shared shell: host-side update over strings, device-array states."""

    jit_update = False
    is_differentiable = False
    full_state_update = True

    def update(self, *args: Any, **kwargs: Any) -> None:  # strings bypass _coerce/jit entirely
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric has already been synced. HINT: call unsync() before calling update()."
            )
        self._host_update(*args, **kwargs)
        self._update_count += 1
        self._update_called = True
        self._computed = None

    def _host_update(self, *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError


class BLEUScore(_HostTextMetric):
    """BLEU (reference ``text/bleu.py:30``).

    Example:
        >>> from torchmetrics_tpu.text import BLEUScore
        >>> metric = BLEUScore()
        >>> metric.update(["the cat is on the mat"], [["the cat is on the mat"]])
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram
        self.add_state("preds_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(n_gram), dist_reduce_fx="sum")

    _tokenizer = staticmethod(_tokenize_fn)

    def _host_update(self, preds: Sequence[str], target: Sequence[Union[str, Sequence[str]]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[t] if isinstance(t, str) else t for t in target]
        num = np.asarray(self._state.tensors["numerator"]).copy()
        den = np.asarray(self._state.tensors["denominator"]).copy()
        p_len, t_len = _bleu_score_update_batched(
            preds_, target_, num, den, float(self.preds_len), float(self.target_len), self.n_gram, self._tokenizer
        )
        self._state.tensors.update(
            preds_len=jnp.asarray(p_len),
            target_len=jnp.asarray(t_len),
            numerator=jnp.asarray(num),
            denominator=jnp.asarray(den),
        )

    def _compute(self, state: Dict[str, Array]) -> Array:
        return _bleu_score_compute(
            state["preds_len"], state["target_len"], state["numerator"], state["denominator"],
            self.n_gram, self.weights, self.smooth,
        )


class SacreBLEUScore(BLEUScore):
    """SacreBLEU (reference ``text/sacre_bleu.py:36``).

    Example:
        >>> from torchmetrics_tpu.text import SacreBLEUScore
        >>> metric = SacreBLEUScore()
        >>> metric.update(["the cat is on the mat"], [["the cat is on the mat"]])
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            _SacreBLEUTokenizer._check_tokenizers_validity(tokenize)
        self._tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)


class _ErrorRateMetric(_HostTextMetric):
    """Shared errors/total sum-scalar shell (WER/CER/MER)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    _update_fn = None  # set per subclass

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _host_update(self, preds, target) -> None:
        errors, total = type(self)._update_fn(preds, target)
        self._state.tensors["errors"] = self._state.tensors["errors"] + errors
        self._state.tensors["total"] = self._state.tensors["total"] + total

    def _compute(self, state: Dict[str, Array]) -> Array:
        return state["errors"] / state["total"]


class WordErrorRate(_ErrorRateMetric):
    """WER (reference ``text/wer.py:28``).

    Example:
        >>> from torchmetrics_tpu.text import WordErrorRate
        >>> metric = WordErrorRate()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> print(f"{float(metric.compute()):.4f}")
        0.2500
    """

    _update_fn = staticmethod(_wer_update)


class CharErrorRate(_ErrorRateMetric):
    """CER (reference ``text/cer.py:28``).

    Example:
        >>> from torchmetrics_tpu.text import CharErrorRate
        >>> metric = CharErrorRate()
        >>> metric.update(["abcd"], ["abce"])
        >>> print(f"{float(metric.compute()):.4f}")
        0.2500
    """

    _update_fn = staticmethod(_cer_update)


class MatchErrorRate(_ErrorRateMetric):
    """MER (reference ``text/mer.py:28``).

    Example:
        >>> from torchmetrics_tpu.text import MatchErrorRate
        >>> metric = MatchErrorRate()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> print(f"{float(metric.compute()):.4f}")
        0.2500
    """

    _update_fn = staticmethod(_mer_update)


class _WordInfoMetric(_HostTextMetric):
    """Shared errors/target_total/preds_total shell (WIL/WIP)."""

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _host_update(self, preds, target) -> None:
        errors, target_total, preds_total = _word_info_update(preds, target)
        t = self._state.tensors
        t["errors"] = t["errors"] + errors
        t["target_total"] = t["target_total"] + target_total
        t["preds_total"] = t["preds_total"] + preds_total


class WordInfoLost(_WordInfoMetric):
    """WIL (reference ``text/wil.py:28``).

    Example:
        >>> from torchmetrics_tpu.text import WordInfoLost
        >>> metric = WordInfoLost()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> print(f"{float(metric.compute()):.4f}")
        0.4375
    """

    higher_is_better = False

    def _compute(self, state):
        return _word_info_lost_compute(state["errors"], state["target_total"], state["preds_total"])


class WordInfoPreserved(_WordInfoMetric):
    """WIP (reference ``text/wip.py:28``).

    Example:
        >>> from torchmetrics_tpu.text import WordInfoPreserved
        >>> metric = WordInfoPreserved()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> print(f"{float(metric.compute()):.4f}")
        0.5625
    """

    higher_is_better = True

    def _compute(self, state):
        return _wip_compute(state["errors"], state["target_total"], state["preds_total"])


class EditDistance(_HostTextMetric):
    """Levenshtein edit distance (reference ``text/edit.py:29``).

    Example:
        >>> from torchmetrics_tpu.text import EditDistance
        >>> metric = EditDistance()
        >>> metric.update(["abcd"], ["abce"])
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(
        self, substitution_cost: int = 1, reduction: Optional[Literal["mean", "sum", "none"]] = "mean", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(substitution_cost, int) and substitution_cost >= 0):
            raise ValueError(
                f"Argument `substitution_cost` must be a positive integer, but got {substitution_cost}"
            )
        allowed = ("mean", "sum", "none", None)
        if reduction not in allowed:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed}, but got {reduction}")
        self.substitution_cost = substitution_cost
        self.reduction = reduction
        if reduction == "none" or reduction is None:
            self.add_state("edit_scores_list", default=[], dist_reduce_fx="cat")
        else:
            self.add_state("edit_scores", jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("num_elements", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _host_update(self, preds, target) -> None:
        distances = _edit_distance_update(preds, target, self.substitution_cost)
        if self.reduction == "none" or self.reduction is None:
            self._state.lists["edit_scores_list"].append(distances)
        else:
            t = self._state.tensors
            t["edit_scores"] = t["edit_scores"] + jnp.sum(distances)
            t["num_elements"] = t["num_elements"] + distances.size

    def _compute(self, state: Dict[str, Any]) -> Array:
        if self.reduction == "none" or self.reduction is None:
            entries = state["edit_scores_list"]
            scores = dim_zero_cat(entries) if isinstance(entries, list) else entries
            return _edit_distance_compute(scores, scores.size, self.reduction)
        return _edit_distance_compute(state["edit_scores"], state["num_elements"], self.reduction)


class Perplexity(Metric):
    """Perplexity (reference ``text/perplexity.py:29``) — fully on-device, jitted.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.text import Perplexity
        >>> probs = np.array([[[0.4, 0.3, 0.3], [0.1, 0.8, 0.1]]], np.float32)
        >>> tokens = np.array([[0, 1]])
        >>> metric = Perplexity()
        >>> metric.update(probs, tokens)
        >>> print(f"{float(metric.compute()):.4f}")
        2.3665
    """

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("count", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        total, count = _perplexity_update(preds, target, self.ignore_index)
        return {
            "total_log_probs": state["total_log_probs"] + total,
            "count": state["count"] + count,
        }

    def _compute(self, state: Dict[str, Array]) -> Array:
        return _perplexity_compute(state["total_log_probs"], state["count"])


class CHRFScore(_HostTextMetric):
    """chrF/chrF++ (reference ``text/chrf.py:32``).

    Example:
        >>> from torchmetrics_tpu.text import CHRFScore
        >>> metric = CHRFScore()
        >>> metric.update(["the cat"], [["the cat"]])
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    _STATE_KEYS = ("preds_char", "preds_word", "target_char", "target_word", "matching_char", "matching_word")

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _validate_chrf_args(n_char_order, n_word_order, beta)
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)
        for key in self._STATE_KEYS:
            size = n_char_order if key.endswith("char") else n_word_order
            self.add_state(key, jnp.zeros(size), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf_score", default=[], dist_reduce_fx="cat")

    def _host_update(self, preds, target) -> None:
        totals = {k: np.asarray(self._state.tensors[k]).copy() for k in self._STATE_KEYS}
        sentence_scores = [] if self.return_sentence_level_score else None
        _chrf_score_update_batched(
            preds, target, totals, self.n_char_order, self.n_word_order, self.n_order, self.beta,
            self.lowercase, self.whitespace, sentence_scores,
        )
        for k in self._STATE_KEYS:
            self._state.tensors[k] = jnp.asarray(totals[k])
        if sentence_scores:
            self._state.lists["sentence_chrf_score"].append(jnp.asarray(sentence_scores, jnp.float32))

    def _compute(self, state: Dict[str, Any]):
        score = _chrf_score_compute({k: state[k] for k in self._STATE_KEYS}, self.n_order, self.beta)
        if self.return_sentence_level_score:
            entries = state["sentence_chrf_score"]
            sentences = dim_zero_cat(entries) if isinstance(entries, list) else entries
            return score, sentences
        return score


class SQuAD(_HostTextMetric):
    """SQuAD EM/F1 (reference ``text/squad.py:29``).

    Example:
        >>> from torchmetrics_tpu.text import SQuAD
        >>> preds = [{"prediction_text": "the cat", "id": "1"}]
        >>> target = [{"answers": {"answer_start": [0], "text": ["the cat"]}, "id": "1"}]
        >>> metric = SQuAD()
        >>> metric.update(preds, target)
        >>> {k: float(v) for k, v in sorted(metric.compute().items())}
        {'exact_match': 100.0, 'f1': 100.0}
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("exact_match", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _host_update(self, preds, target) -> None:
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1, exact_match, total = _squad_update(preds_dict, target_dict)
        t = self._state.tensors
        t["f1_score"] = t["f1_score"] + f1
        t["exact_match"] = t["exact_match"] + exact_match
        t["total"] = t["total"] + total

    def _compute(self, state: Dict[str, Array]) -> Dict[str, Array]:
        return _squad_compute(state["f1_score"], state["exact_match"], state["total"])


class ROUGEScore(_HostTextMetric):
    """ROUGE-N / ROUGE-L / ROUGE-LSum (reference ``text/rouge.py:36``).

    List states per ``{rouge_key}_{precision,recall,fmeasure}`` triple, ``dist_reduce_fx=None``
    (reference ``text/rouge.py:143``).

    Example:
        >>> from torchmetrics_tpu.text import ROUGEScore
        >>> metric = ROUGEScore(rouge_keys=('rouge1',))
        >>> metric.update("the cat sat", "a cat sat")
        >>> {k: round(float(v), 4) for k, v in sorted(metric.compute().items())}
        {'rouge1_fmeasure': 0.6667, 'rouge1_precision': 0.6667, 'rouge1_recall': 0.6667}
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        use_stemmer: bool = False,
        normalizer=None,
        tokenizer=None,
        accumulate: str = "best",
        rouge_keys=("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from torchmetrics_tpu.functional.text.rouge import (
            ALLOWED_ACCUMULATE_VALUES,
            ALLOWED_ROUGE_KEYS,
            _stemmer_or_none,
        )

        if not isinstance(rouge_keys, tuple):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(
                    f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}"
                )
        if accumulate not in ALLOWED_ACCUMULATE_VALUES:
            raise ValueError(
                f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
            )
        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[k] for k in rouge_keys]
        self.stemmer = _stemmer_or_none(use_stemmer)
        self.normalizer = normalizer
        self.tokenizer = tokenizer
        self.accumulate = accumulate
        for rouge_key in self.rouge_keys:
            for score in ("fmeasure", "precision", "recall"):
                self.add_state(f"{rouge_key}_{score}", [], dist_reduce_fx=None)

    def _host_update(self, preds, target) -> None:
        from torchmetrics_tpu.functional.text.rouge import _rouge_score_update

        # same nesting normalisation as functional rouge_score: a flat list of target strings is
        # a multi-reference set when there is a single prediction (the reference module wraps by
        # isinstance(preds, str) and silently zip-truncates for 1-element pred lists)
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(target, str):
            target = [[target]]
        elif isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
            target = [[tgt] for tgt in target] if len(preds) > 1 else [list(target)]
        output = _rouge_score_update(
            preds, target, self.rouge_keys_values, accumulate=self.accumulate,
            stemmer=self.stemmer, normalizer=self.normalizer, tokenizer=self.tokenizer,
        )
        for key_val, key_name in zip(self.rouge_keys_values, self.rouge_keys):
            for metric in output[key_val]:
                for tp, value in metric.items():
                    self._state.lists[f"{key_name}_{tp}"].append(jnp.asarray([value], jnp.float32))

    def _compute(self, state: Dict[str, Any]) -> Dict[str, Array]:
        out: Dict[str, Array] = {}
        for rouge_key in self.rouge_keys:
            for score in ("fmeasure", "precision", "recall"):
                vals = state[f"{rouge_key}_{score}"]
                if isinstance(vals, list):
                    vals = dim_zero_cat(vals) if vals else jnp.zeros((0,))
                out[f"{rouge_key}_{score}"] = jnp.mean(vals) if vals.size else jnp.asarray(0.0)
        return out


class TranslationEditRate(_HostTextMetric):
    """TER (reference ``text/ter.py:30``).

    Example:
        >>> from torchmetrics_tpu.text import TranslationEditRate
        >>> metric = TranslationEditRate()
        >>> metric.update(["the cat is on the mat"], [["the cat is on a mat"]])
        >>> print(f"{float(metric.compute()):.4f}")
        0.1667
    """

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        from torchmetrics_tpu.functional.text.ter import _TercomTokenizer

        for name, val in (
            ("normalize", normalize), ("no_punctuation", no_punctuation),
            ("lowercase", lowercase), ("asian_support", asian_support),
        ):
            if not isinstance(val, bool):
                raise ValueError(f"Expected argument `{name}` to be of type boolean but got {val}.")
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score
        self.add_state("total_num_edits", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_tgt_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def _host_update(self, preds, target) -> None:
        from torchmetrics_tpu.functional.text.ter import _ter_update

        sentence: Optional[list] = [] if self.return_sentence_level_score else None
        num_edits, tgt_len, sentence = _ter_update(
            preds, target, self.tokenizer, float(self.total_num_edits), float(self.total_tgt_len), sentence
        )
        t = self._state.tensors
        t["total_num_edits"] = jnp.asarray(num_edits, jnp.float32)
        t["total_tgt_len"] = jnp.asarray(tgt_len, jnp.float32)
        if sentence is not None:
            self._state.lists["sentence_ter"].extend(jnp.asarray([s], jnp.float32) for s in sentence)

    def _compute(self, state: Dict[str, Any]):
        edits = jnp.asarray(state["total_num_edits"], jnp.float32)
        tgt_len = jnp.asarray(state["total_tgt_len"], jnp.float32)
        # trace-safe form of _compute_ter_score_from_statistics
        ter = jnp.where(
            (tgt_len > 0) & (edits > 0),
            edits / jnp.where(tgt_len > 0, tgt_len, 1.0),
            jnp.where((tgt_len == 0) & (edits > 0), 1.0, 0.0),
        )
        if self.return_sentence_level_score:
            sent = state["sentence_ter"]
            if isinstance(sent, list):
                sent = dim_zero_cat(sent) if sent else jnp.zeros((0,))
            return ter, sent
        return ter


class ExtendedEditDistance(_HostTextMetric):
    """EED (reference ``text/eed.py:27``).

    Example:
        >>> from torchmetrics_tpu.text import ExtendedEditDistance
        >>> metric = ExtendedEditDistance()
        >>> metric.update(["this is the prediction"], ["this is the reference"])
        >>> print(f"{float(metric.compute()):.4f}")
        0.3835
    """

    higher_is_better = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        for name, val in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
            if not isinstance(val, float) or val < 0:
                raise ValueError(f"Parameter `{name}` must be a non-negative float.")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion
        self.add_state("sentence_eed", [], dist_reduce_fx="cat")

    def _host_update(self, preds, target) -> None:
        from torchmetrics_tpu.functional.text.eed import _eed_update

        scores = _eed_update(
            preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion
        )
        self._state.lists["sentence_eed"].extend(jnp.asarray([s], jnp.float32) for s in scores)

    def _compute(self, state: Dict[str, Any]):
        sent = state["sentence_eed"]
        if isinstance(sent, list):
            sent = dim_zero_cat(sent) if sent else jnp.zeros((0,))
        avg = jnp.mean(sent) if sent.size else jnp.asarray(0.0)
        if self.return_sentence_level_score:
            return avg, sent
        return avg


class _SentenceStoreTextMetric(_HostTextMetric):
    """Shared shell for model-based text metrics that must keep raw sentences until compute.

    Raw strings cannot live in array states, so they are plain host lists: ``forward`` computes
    the batch value directly on the batch (no snapshot/reset dance over string storage), reset
    clears them, and cross-process ``sync`` of these metrics is NOT supported (documented
    divergence — the reference syncs tokenised id tensors instead; gather sentences externally
    or compute per process).
    """

    jit_compute = False  # compute reads host sentence lists, never cacheable as traced constants

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self._preds: list = []
        self._target: list = []

    @staticmethod
    def _coerce_sentences(preds, target):
        preds = [preds] if isinstance(preds, str) else list(preds)
        target = [target] if isinstance(target, str) else list(target)
        if len(preds) != len(target):
            raise ValueError(
                f"Number of predicted and reference sentences must match: {len(preds)} != {len(target)}"
            )
        return preds, target

    def _host_update(self, preds, target) -> None:
        preds, target = self._coerce_sentences(preds, target)
        self._preds.extend(preds)
        self._target.extend(target)

    def _score(self, preds: list, target: list):
        raise NotImplementedError

    def _compute(self, state: Dict[str, Any]):
        return self._score(self._preds, self._target)

    def forward(self, preds, target):  # noqa: D102 - batch value computed on the batch alone
        self.update(preds, target)
        batch_preds, batch_target = self._coerce_sentences(preds, target)
        return self._score(batch_preds, batch_target)

    def reset(self) -> None:  # noqa: D102
        super().reset()
        self._preds = []
        self._target = []


def _check_inert_knobs(num_layers="skip", verbose="skip", device="skip",
                       batch_size="skip", num_threads="skip") -> None:
    """The inert reference knobs sit mid-signature; a positional caller who misbinds a
    callable/model onto one of them must get an error, never silently-wrong scores."""
    if num_layers != "skip" and not (num_layers is None or isinstance(num_layers, int)):
        raise TypeError(f"`num_layers` must be an int or None, got {type(num_layers).__name__}")
    if verbose != "skip" and not isinstance(verbose, bool):
        raise TypeError(f"`verbose` must be a bool, got {type(verbose).__name__}")
    if device != "skip" and callable(device):
        raise TypeError("`device` received a callable — check your positional arguments")
    if batch_size != "skip" and not isinstance(batch_size, int):
        raise TypeError(f"`batch_size` must be an int, got {type(batch_size).__name__}")
    if num_threads != "skip" and not isinstance(num_threads, int):
        raise TypeError(f"`num_threads` must be an int, got {type(num_threads).__name__}")


class BERTScore(_SentenceStoreTextMetric):
    """BERTScore (reference ``text/bert.py:54``): pluggable-encoder design.

    Sentences accumulate on the host (see the base class); the greedy cosine matching runs as
    jnp MXU matmuls at compute time.

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from torchmetrics_tpu.text import BERTScore
        >>> table = np.random.RandomState(0).randn(64, 8).astype(np.float32)
        >>> def toy_encoder(sentences):  # any callable (sentences) -> (emb, mask) works
        ...     rows = [[hash(w) % 64 for w in s.split()] for s in sentences]
        ...     width = max(len(r) for r in rows)
        ...     emb = np.zeros((len(rows), width, 8), np.float32)
        ...     mask = np.zeros((len(rows), width), np.int32)
        ...     for i, r in enumerate(rows):
        ...         emb[i, :len(r)], mask[i, :len(r)] = table[r], 1
        ...     return jnp.asarray(emb), jnp.asarray(mask)
        >>> metric = BERTScore(encoder=toy_encoder)
        >>> metric.update(["the cat sat"], ["the cat sat"])
        >>> print(f"{float(np.asarray(metric.compute()['f1']).reshape(-1)[0]):.4f}")
        1.0000
    """

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model=None,
        user_tokenizer=None,
        user_forward_fn=None,
        verbose: bool = False,
        idf: bool = False,
        device=None,
        max_length: int = 512,
        batch_size: int = 64,
        num_threads: int = 0,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        encoder=None,
        tokenize=None,
        **kwargs: Any,
    ) -> None:
        """Reference signature (``text/bert.py:134-153``) plus this build's pluggable
        ``encoder``/``tokenize`` callables; ``verbose``/``device``/``batch_size``/``num_threads``
        are inert host-loop knobs here, ``baseline_url`` would need network egress."""
        super().__init__(**kwargs)
        _check_inert_knobs(num_layers=num_layers, verbose=verbose, device=device,
                           batch_size=batch_size, num_threads=num_threads)
        if baseline_url is not None:
            rank_zero_warn("`baseline_url` needs network egress, which this build does not have;"
                           " pass `baseline_path` instead.")
        user_hooks = model is not None or user_tokenizer is not None or user_forward_fn is not None
        # the default-model encoder (incl. the all_layers layer-stacked variant) is built ONCE
        # here and reused across every compute()/update cycle — rebuilding the HF model per
        # _score call would reload checkpoint weights each epoch
        if encoder is None and not user_hooks:
            from torchmetrics_tpu.functional.text.bert import _DEFAULT_MODEL
            from torchmetrics_tpu.utils.pretrained import bert_encoder as _build

            if model_name_or_path is None:
                rank_zero_warn(
                    "The argument `model_name_or_path` was not specified while it is required when the default"
                    " `transformers` model is used."
                    f" It will use the default recommended model - {_DEFAULT_MODEL!r}."
                )
                model_name_or_path = _DEFAULT_MODEL
            encoder, tokenize = _build(
                model_name_or_path, num_layers=num_layers, max_length=max_length, all_layers=all_layers
            )
        self.model_name_or_path = model_name_or_path
        self.encoder = encoder
        self.tokenize = tokenize
        self.num_layers = num_layers
        self.all_layers = all_layers
        self.own_model = model
        self.user_tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self.max_length = max_length
        self.return_hash = return_hash
        self.idf = idf
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_path = baseline_path
        self.lang = lang

    def _score(self, preds: list, target: list):
        from torchmetrics_tpu.functional.text.bert import bert_score

        hooks = {}
        if self.own_model is not None or self.user_tokenizer is not None or self.user_forward_fn is not None:
            hooks = {
                "own_model": self.own_model,
                "user_tokenizer": self.user_tokenizer,
                "user_forward_fn": self.user_forward_fn,
            }
        return bert_score(
            preds,
            target,
            model_name_or_path=self.model_name_or_path,
            encoder=self.encoder,
            tokenize=self.tokenize,
            num_layers=self.num_layers,
            max_length=self.max_length,
            idf=self.idf,
            rescale_with_baseline=self.rescale_with_baseline,
            baseline_path=self.baseline_path,
            lang=self.lang,
            all_layers=self.all_layers,
            return_hash=self.return_hash,
            **hooks,
        )


class InfoLM(_SentenceStoreTextMetric):
    """InfoLM (reference ``text/infolm.py:40``): pluggable masked-LM design with the
    reference's defaults (``bert-base-uncased``, ``temperature=0.25``, ``idf=True``).

    Example:
        >>> from torchmetrics_tpu.text import InfoLM
        >>> metric = InfoLM('google/bert_uncased_L-2_H-128_A-2', idf=False)  # doctest: +SKIP
        >>> metric.update(['he read the book'], ['he reads the book'])  # doctest: +SKIP
        >>> metric.compute()  # doctest: +SKIP
    """

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        model_name_or_path: str = "bert-base-uncased",
        temperature: float = 0.25,
        information_measure: str = "kl_divergence",
        idf: bool = True,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        device=None,
        max_length: Optional[int] = None,
        batch_size: int = 64,
        num_threads: int = 0,
        verbose: bool = True,
        return_sentence_level_score: bool = False,
        masked_lm=None,
        tokenize=None,
        **kwargs: Any,
    ) -> None:
        """Reference signature (``text/infolm.py:120-134``; ``device``/``batch_size``/
        ``num_threads``/``verbose`` are inert host-loop knobs here) plus this build's
        pluggable ``masked_lm``/``tokenize`` callables."""
        _check_inert_knobs(verbose=verbose, device=device, batch_size=batch_size,
                           num_threads=num_threads)
        # max_length=None resolves to model.config.max_length inside _hf_masked_lm
        # (the reference's default, functional/text/infolm.py:634)
        super().__init__(**kwargs)
        from torchmetrics_tpu.functional.text.infolm import _hf_masked_lm, _validate_measure

        _validate_measure(information_measure, alpha, beta)
        if not (isinstance(temperature, (int, float)) and temperature > 0):
            raise ValueError(f"Argument `temperature` must be a positive number, but got {temperature}")
        if masked_lm is None:
            masked_lm, tokenize = _hf_masked_lm(model_name_or_path, max_length=max_length, temperature=temperature)
        if idf and tokenize is None:
            raise ValueError(
                "`idf=True` needs token ids: pass `tokenize` alongside a custom `masked_lm`, or use"
                " a HuggingFace `model_name_or_path` so the tokenizer is resolved automatically."
            )
        self.masked_lm = masked_lm
        self.tokenize = tokenize
        self.idf = idf
        self.information_measure = information_measure
        self.alpha = alpha
        self.beta = beta
        self.return_sentence_level_score = return_sentence_level_score

    def _score(self, preds: list, target: list):
        from torchmetrics_tpu.functional.text.infolm import infolm

        return infolm(
            preds, target, masked_lm=self.masked_lm, tokenize=self.tokenize, idf=self.idf,
            information_measure=self.information_measure, alpha=self.alpha, beta=self.beta,
            return_sentence_level_score=self.return_sentence_level_score,
        )
