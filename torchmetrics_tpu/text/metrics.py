"""Stateful text metrics (reference ``src/torchmetrics/text/*.py``).

String inputs cannot be traced, so text metric updates run the host counting path and fold
results into fixed-shape device states (``jit_update=False``); computes are trace-safe jnp.
State layouts follow the reference: BLEU keeps (n_gram,) count vectors (``text/bleu.py:91-94``),
the error-rate family keeps 2-4 sum scalars (``text/wer.py:82-83``), chrF keeps six per-order
vectors (vs the reference's dicts of scalars, ``text/chrf.py:131-146``).
"""
from __future__ import annotations

from typing import Any, Dict, Literal, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu.functional.text._edit import edit_distance_batch
from torchmetrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn
from torchmetrics_tpu.functional.text.chrf import (
    _chrf_score_compute,
    _chrf_score_update,
    _validate_chrf_args,
)
from torchmetrics_tpu.functional.text.edit import _edit_distance_compute, _edit_distance_update
from torchmetrics_tpu.functional.text.perplexity import _perplexity_compute, _perplexity_update
from torchmetrics_tpu.functional.text.sacre_bleu import AVAILABLE_TOKENIZERS, _SacreBLEUTokenizer
from torchmetrics_tpu.functional.text.squad import _squad_compute, _squad_input_check, _squad_update
from torchmetrics_tpu.functional.text.wer import (
    _cer_update,
    _mer_update,
    _wer_update,
    _word_info_update,
    _wip_compute,
    _word_info_lost_compute,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.data import dim_zero_cat
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError


class _HostTextMetric(Metric):
    """Shared shell: host-side update over strings, device-array states."""

    jit_update = False
    is_differentiable = False
    full_state_update = True

    def update(self, *args: Any, **kwargs: Any) -> None:  # strings bypass _coerce/jit entirely
        if self._is_synced:
            raise TorchMetricsUserError(
                "The Metric has already been synced. HINT: call unsync() before calling update()."
            )
        self._host_update(*args, **kwargs)
        self._update_count += 1
        self._update_called = True
        self._computed = None

    def _host_update(self, *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError


class BLEUScore(_HostTextMetric):
    """BLEU (reference ``text/bleu.py:30``)."""

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram
        self.add_state("preds_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(n_gram), dist_reduce_fx="sum")

    _tokenizer = staticmethod(_tokenize_fn)

    def _host_update(self, preds: Sequence[str], target: Sequence[Union[str, Sequence[str]]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[t] if isinstance(t, str) else t for t in target]
        num = np.asarray(self._state.tensors["numerator"]).copy()
        den = np.asarray(self._state.tensors["denominator"]).copy()
        p_len, t_len = _bleu_score_update(
            preds_, target_, num, den, float(self.preds_len), float(self.target_len), self.n_gram, self._tokenizer
        )
        self._state.tensors.update(
            preds_len=jnp.asarray(p_len),
            target_len=jnp.asarray(t_len),
            numerator=jnp.asarray(num),
            denominator=jnp.asarray(den),
        )

    def _compute(self, state: Dict[str, Array]) -> Array:
        return _bleu_score_compute(
            state["preds_len"], state["target_len"], state["numerator"], state["denominator"],
            self.n_gram, self.weights, self.smooth,
        )


class SacreBLEUScore(BLEUScore):
    """SacreBLEU (reference ``text/sacre_bleu.py:36``)."""

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, weights=weights, **kwargs)
        if tokenize not in AVAILABLE_TOKENIZERS:
            _SacreBLEUTokenizer._check_tokenizers_validity(tokenize)
        self._tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)


class _ErrorRateMetric(_HostTextMetric):
    """Shared errors/total sum-scalar shell (WER/CER/MER)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    _update_fn = None  # set per subclass

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _host_update(self, preds, target) -> None:
        errors, total = type(self)._update_fn(preds, target)
        self._state.tensors["errors"] = self._state.tensors["errors"] + errors
        self._state.tensors["total"] = self._state.tensors["total"] + total

    def _compute(self, state: Dict[str, Array]) -> Array:
        return state["errors"] / state["total"]


class WordErrorRate(_ErrorRateMetric):
    """WER (reference ``text/wer.py:28``)."""

    _update_fn = staticmethod(_wer_update)


class CharErrorRate(_ErrorRateMetric):
    """CER (reference ``text/cer.py:28``)."""

    _update_fn = staticmethod(_cer_update)


class MatchErrorRate(_ErrorRateMetric):
    """MER (reference ``text/mer.py:28``)."""

    _update_fn = staticmethod(_mer_update)


class _WordInfoMetric(_HostTextMetric):
    """Shared errors/target_total/preds_total shell (WIL/WIP)."""

    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _host_update(self, preds, target) -> None:
        errors, target_total, preds_total = _word_info_update(preds, target)
        t = self._state.tensors
        t["errors"] = t["errors"] + errors
        t["target_total"] = t["target_total"] + target_total
        t["preds_total"] = t["preds_total"] + preds_total


class WordInfoLost(_WordInfoMetric):
    """WIL (reference ``text/wil.py:28``)."""

    higher_is_better = False

    def _compute(self, state):
        return _word_info_lost_compute(state["errors"], state["target_total"], state["preds_total"])


class WordInfoPreserved(_WordInfoMetric):
    """WIP (reference ``text/wip.py:28``)."""

    higher_is_better = True

    def _compute(self, state):
        return _wip_compute(state["errors"], state["target_total"], state["preds_total"])


class EditDistance(_HostTextMetric):
    """Levenshtein edit distance (reference ``text/edit.py:29``)."""

    higher_is_better = False
    plot_lower_bound = 0.0

    def __init__(
        self, substitution_cost: int = 1, reduction: Optional[Literal["mean", "sum", "none"]] = "mean", **kwargs: Any
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(substitution_cost, int) and substitution_cost >= 0):
            raise ValueError(
                f"Expected argument `substitution_cost` to be a positive integer, but got {substitution_cost}"
            )
        allowed = ("mean", "sum", "none", None)
        if reduction not in allowed:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed}, but got {reduction}")
        self.substitution_cost = substitution_cost
        self.reduction = reduction
        if reduction == "none" or reduction is None:
            self.add_state("edit_scores_list", default=[], dist_reduce_fx="cat")
        else:
            self.add_state("edit_scores", jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("num_elements", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _host_update(self, preds, target) -> None:
        distances = _edit_distance_update(preds, target, self.substitution_cost)
        if self.reduction == "none" or self.reduction is None:
            self._state.lists["edit_scores_list"].append(distances)
        else:
            t = self._state.tensors
            t["edit_scores"] = t["edit_scores"] + jnp.sum(distances)
            t["num_elements"] = t["num_elements"] + distances.size

    def _compute(self, state: Dict[str, Any]) -> Array:
        if self.reduction == "none" or self.reduction is None:
            entries = state["edit_scores_list"]
            scores = dim_zero_cat(entries) if isinstance(entries, list) else entries
            return _edit_distance_compute(scores, scores.size, self.reduction)
        return _edit_distance_compute(state["edit_scores"], state["num_elements"], self.reduction)


class Perplexity(Metric):
    """Perplexity (reference ``text/perplexity.py:29``) — fully on-device, jitted."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(self, ignore_index: Optional[int] = None, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError(f"Argument `ignore_index` expected to either be `None` or an `int` but got {ignore_index}")
        self.ignore_index = ignore_index
        self.add_state("total_log_probs", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("count", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _update(self, state: Dict[str, Array], preds: Array, target: Array) -> Dict[str, Array]:
        total, count = _perplexity_update(preds, target, self.ignore_index)
        return {
            "total_log_probs": state["total_log_probs"] + total,
            "count": state["count"] + count,
        }

    def _compute(self, state: Dict[str, Array]) -> Array:
        return _perplexity_compute(state["total_log_probs"], state["count"])


class CHRFScore(_HostTextMetric):
    """chrF/chrF++ (reference ``text/chrf.py:32``)."""

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    _STATE_KEYS = ("preds_char", "preds_word", "target_char", "target_word", "matching_char", "matching_word")

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _validate_chrf_args(n_char_order, n_word_order, beta)
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score
        self.n_order = float(n_char_order + n_word_order)
        for key in self._STATE_KEYS:
            size = n_char_order if key.endswith("char") else n_word_order
            self.add_state(key, jnp.zeros(size), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf_score", default=[], dist_reduce_fx="cat")

    def _host_update(self, preds, target) -> None:
        totals = {k: np.asarray(self._state.tensors[k]).copy() for k in self._STATE_KEYS}
        sentence_scores = [] if self.return_sentence_level_score else None
        _chrf_score_update(
            preds, target, totals, self.n_char_order, self.n_word_order, self.n_order, self.beta,
            self.lowercase, self.whitespace, sentence_scores,
        )
        for k in self._STATE_KEYS:
            self._state.tensors[k] = jnp.asarray(totals[k])
        if sentence_scores:
            self._state.lists["sentence_chrf_score"].append(jnp.asarray(sentence_scores, jnp.float32))

    def _compute(self, state: Dict[str, Any]):
        score = _chrf_score_compute({k: state[k] for k in self._STATE_KEYS}, self.n_order, self.beta)
        if self.return_sentence_level_score:
            entries = state["sentence_chrf_score"]
            sentences = dim_zero_cat(entries) if isinstance(entries, list) else entries
            return score, sentences
        return score


class SQuAD(_HostTextMetric):
    """SQuAD EM/F1 (reference ``text/squad.py:29``)."""

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 100.0

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("exact_match", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def _host_update(self, preds, target) -> None:
        preds_dict, target_dict = _squad_input_check(preds, target)
        f1, exact_match, total = _squad_update(preds_dict, target_dict)
        t = self._state.tensors
        t["f1_score"] = t["f1_score"] + f1
        t["exact_match"] = t["exact_match"] + exact_match
        t["total"] = t["total"] + total

    def _compute(self, state: Dict[str, Array]) -> Dict[str, Array]:
        return _squad_compute(state["f1_score"], state["exact_match"], state["total"])
