"""Text module metrics (reference ``src/torchmetrics/text/``)."""
from torchmetrics_tpu.text.metrics import (
    BLEUScore,
    CharErrorRate,
    CHRFScore,
    EditDistance,
    ExtendedEditDistance,
    ROUGEScore,
    TranslationEditRate,
    MatchErrorRate,
    Perplexity,
    SacreBLEUScore,
    SQuAD,
    WordErrorRate,
    WordInfoLost,
    WordInfoPreserved,
)

__all__ = [
    "BLEUScore",
    "CHRFScore",
    "CharErrorRate",
    "EditDistance",
    "ExtendedEditDistance",
    "MatchErrorRate",
    "ROUGEScore",
    "TranslationEditRate",
    "Perplexity",
    "SQuAD",
    "SacreBLEUScore",
    "WordErrorRate",
    "WordInfoLost",
    "WordInfoPreserved",
]
