"""Keyed multi-tenant metrics: one kernel, a million streams.

One :class:`~torchmetrics_tpu.metric.Metric` instance owns one logical stream; serving
per-user / per-slice metrics for millions of tenants as a dict of instances means millions
of per-step dispatches — the host-overhead regime the fast-dispatch tiers exist to kill.
:class:`KeyedMetric` vectorizes the tenant axis instead: every state carries a leading
``[num_keys, ...]`` axis (one fixed-shape resident table), and ``update(key_ids, ...)``
routes a mixed-tenant batch through ONE fused launch — segment reductions for
sum/max/min-shaped states, a vmap fallback otherwise. See ``docs/keyed.md``.
"""
from torchmetrics_tpu.keyed.engine import STRATEGIES, KeyedMetric, KeyedMetricCollection

__all__ = ["KeyedMetric", "KeyedMetricCollection", "STRATEGIES"]
