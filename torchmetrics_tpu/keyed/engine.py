"""The keyed multi-tenant engine: ``KeyedMetric`` / ``KeyedMetricCollection``.

Design (docs/keyed.md):

- **State**: for every tensor state of the template metric, the keyed metric registers the
  same state with a leading ``[num_keys, ...]`` tenant axis — the whole tenant table is one
  fixed-shape resident device buffer (memory ``num_keys x state_size``), so the dispatch
  tiers, donation, snapshots, the journal, and ``process_sync`` all see an ordinary metric
  with bigger states. List ("cat") states cannot be keyed (unbounded per-tenant shape).

- **Update routing** (``update(key_ids, *batch)``), one fused XLA program either way:

  * ``segments`` — the fast path for metrics whose update *decomposes per element* under
    their registered reductions (every state ``sum``/``max``/``min``-reduced): the
    template's own ``_update`` is vmapped over the batch elements against the defaults
    (so masking/NaN handling/dtype rules are inherited, never re-implemented), and each
    state's per-element contributions are folded into the tenant table with ONE segment
    reduction (``ops/segments.py``). Cost ``O(batch)``, independent of ``num_keys``.
  * ``vmap`` — the general fallback: the per-key sequential fold is vmapped across the
    tenant axis; each key scans the batch, applies the template update speculatively,
    and commits it only for its own elements. Bit-identical to a per-instance loop BY
    CONSTRUCTION (same op order per key), but costs ``O(num_keys x batch)`` — right for
    non-decomposable metrics at modest ``num_keys``, wrong at a million.

- **Dispatch**: the keyed update is just another compiled kernel. ``fast_update`` opts the
  class into the AOT single-update tier (``Metric._fast_update``): steady-state updates go
  through a compiled executable with the ``[num_keys, ...]`` state buffers donated.
  ``update_batches`` / ``buffered(k)`` ride the inherited whole-stack scan.

- **Compute** (``compute(keys=...)``): a vectorized gather — only the requested rows of
  the tenant table are materialized and the template's ``_compute`` is vmapped over them.
  ``compute()`` with no keys finalises all ``num_keys`` streams in one program.

- **Robustness**: ``snapshot()`` blobs gain a ``keys`` descriptor (validated on restore —
  ``robust/checkpoint.py``), the write-ahead journal records ``(key_ids, batch)`` and
  replays bit-identically, and ``process_sync`` reduces the keyed states elementwise
  across ranks through the existing bounded/quorum path.

- **Scale-out** (``KeyedMetric(...).shard(mesh)``, docs/distributed.md "Sharded state"):
  the ``[num_keys, ...]`` tenant axis is exactly the shape the mesh layer shards — the
  table partitions its leading axis across the devices, every tier accumulates
  shard-local (bit-identical to replicated, segments strategy preserved), and the
  multi-process sync reduce-scatters the table lazily instead of allgathering
  ``world`` full copies.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from torchmetrics_tpu import obs
from torchmetrics_tpu.collections import MetricCollection
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.ops import dispatch as _dispatch
from torchmetrics_tpu.ops import segments as _segments
from torchmetrics_tpu.utils.checks import is_traced
from torchmetrics_tpu.utils.exceptions import TorchMetricsUserError

#: update-routing strategies: "auto" picks segments when the template decomposes
STRATEGIES = ("auto", "segments", "vmap")

_SUM_FX = ("sum", jnp.sum)
_MAX_FX = ("max", jnp.max)
_MIN_FX = ("min", jnp.min)


class KeyedMetric(Metric):
    """One metric, ``num_keys`` independent logical streams, one kernel per batch.

    ``metric`` is the template: an instance (or zero-arg-constructible class) whose
    ``_update``/``_compute`` kernels and registered states define the per-key semantics.
    The template instance itself is never updated — it is the source of the kernels and
    defaults only.

    Sketch-state templates (docs/sketches.md) key like any other metric: sum-merged
    sketches (the curve family's ``approx="sketch"`` histogram pair) decompose under the
    segment strategy, while KLL-backed templates (``StreamingQuantile``) declare
    ``keyed_decomposable = False`` and take the per-element vmap fallback.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import SumMetric
        >>> from torchmetrics_tpu.keyed import KeyedMetric
        >>> km = KeyedMetric(SumMetric, num_keys=4)
        >>> km.update(np.array([0, 2, 0, 2]), np.array([1.0, 10.0, 2.0, 20.0]))
        >>> np.asarray(km.compute()).tolist()          # every stream, one launch
        [3.0, 0.0, 30.0, 0.0]
        >>> np.asarray(km.compute(keys=[2])).tolist()  # lazy per-key gather
        [30.0]
    """

    #: the keyed update is an update-only protocol: opt into the AOT+donation update tier
    fast_update = True

    def __init__(
        self,
        metric: Union[Metric, type],
        num_keys: int,
        strategy: str = "auto",
        validate_keys: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if isinstance(metric, type):
            if not issubclass(metric, Metric):
                raise ValueError(f"Expected a Metric instance or subclass, got {metric!r}")
            metric = metric()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected a Metric instance or subclass, got {metric!r}")
        if isinstance(metric, KeyedMetric):
            raise ValueError("KeyedMetric cannot be nested: pass the plain template metric")
        num_keys = int(num_keys)
        if num_keys < 1:
            raise ValueError(f"KeyedMetric needs num_keys >= 1, got {num_keys}")
        if metric._state.lists:
            raise TorchMetricsUserError(
                f"{type(metric).__name__} holds list ('cat') states, which have no fixed"
                " per-key shape — only tensor-state metrics can be keyed. Bound the state"
                " first (e.g. a binned/sketched variant) and key that."
            )
        if not (metric.jit_update and metric.jit_compute):
            raise TorchMetricsUserError(
                f"{type(metric).__name__} opts out of jit (jit_update/jit_compute=False):"
                " its kernels cannot trace into the fused keyed program."
            )
        self._template = metric
        self.num_keys = num_keys
        self.validate_keys = bool(validate_keys)
        self._tpl_names = tuple(metric._state.tensors)
        self._strategy = self._resolve_strategy(strategy)
        for name in self._tpl_names:
            default = metric._defaults[name]
            keyed_default = jnp.broadcast_to(default, (num_keys,) + tuple(jnp.shape(default)))
            self.add_state(name, keyed_default, dist_reduce_fx=metric._reductions[name])
        # host-side activity tracking (telemetry only): which keys ever saw an update
        self._seen_keys = np.zeros(num_keys, dtype=bool)
        self._active_count = 0

    # ------------------------------------------------------------------ strategy
    def _decomposable(self) -> bool:
        """True when every template state merges per element under segment reductions."""
        for name in self._tpl_names:
            fx = self._template._reductions[name]
            if fx in _SUM_FX or fx in _MAX_FX or fx in _MIN_FX:
                continue
            return False
        return True

    def _resolve_strategy(self, strategy: str) -> str:
        if strategy not in STRATEGIES:
            raise ValueError(f"KeyedMetric strategy must be one of {STRATEGIES}, got {strategy!r}")
        if strategy == "segments":
            if not self._decomposable():
                raise TorchMetricsUserError(
                    f"{type(self._template).__name__} does not decompose under segment"
                    " reductions (a state's dist_reduce_fx is not sum/max/min) — use"
                    " strategy='vmap' (or 'auto')."
                )
            return strategy
        if strategy == "vmap":
            return strategy
        hint = type(self._template).keyed_decomposable
        if hint is not None:
            return "segments" if hint else "vmap"
        return "segments" if self._decomposable() else "vmap"

    @property
    def strategy(self) -> str:
        """Resolved update-routing strategy: ``"segments"`` or ``"vmap"``."""
        return self._strategy

    @property
    def template(self) -> Metric:
        """The template metric the per-key kernels come from (never updated itself)."""
        return self._template

    @property
    def active_keys(self) -> int:
        """Keys this instance has seen at least one (host-visible) update for.

        Best-effort telemetry: key ids arriving as tracers (inside an outer jit) cannot
        be inspected without a host sync and are not counted.
        """
        return self._active_count

    # ------------------------------------------------------------------ kernels
    def _update(self, state: Dict[str, Array], key_ids: Array, *args: Any, **kwargs: Any) -> Dict[str, Array]:
        key_ids = jnp.asarray(key_ids)
        if not jnp.issubdtype(key_ids.dtype, jnp.integer):
            raise TorchMetricsUserError(
                f"key_ids must be an integer array, got dtype {key_ids.dtype}"
            )
        if self._strategy == "segments":
            return self._segment_update(state, key_ids, args, kwargs)
        return self._vmap_update(state, key_ids, args, kwargs)

    def _segment_update(
        self, state: Dict[str, Array], key_ids: Array, args: tuple, kwargs: dict
    ) -> Dict[str, Array]:
        """Per-element contributions via the template's OWN kernel, one segment reduce per state."""
        tpl = self._template
        defaults = {n: tpl._defaults[n] for n in self._tpl_names}
        upd = tpl._update

        def _elem(e_args: tuple, e_kwargs: dict) -> Dict[str, Array]:
            out = upd(dict(defaults), *e_args, **e_kwargs)
            return {n: out.get(n, defaults[n]) for n in defaults}

        contribs = jax.vmap(_elem)(args, kwargs)  # {name: [batch, *state_shape]}
        n_keys = self.num_keys
        new: Dict[str, Array] = {}
        for name in self._tpl_names:
            fx = self._reductions[name]
            cur = state[name]
            c = contribs[name]
            if fx in _SUM_FX:
                # the per-element output includes the default; sum defaults are typically
                # zero but subtracting keeps custom non-zero defaults exact
                seg = _segments.segment_sum(c - defaults[name], key_ids, n_keys)
                new[name] = cur + seg.astype(cur.dtype)
            elif fx in _MAX_FX:
                # empty segments come back as the dtype's identity (-inf): a no-op merge
                seg = _segments.segment_max(c, key_ids, n_keys)
                new[name] = jnp.maximum(cur, seg.astype(cur.dtype))
            else:  # _MIN_FX — _resolve_strategy guarantees nothing else reaches here
                seg = _segments.segment_min(c, key_ids, n_keys)
                new[name] = jnp.minimum(cur, seg.astype(cur.dtype))
        return new

    def _vmap_update(
        self, state: Dict[str, Array], key_ids: Array, args: tuple, kwargs: dict
    ) -> Dict[str, Array]:
        """General fallback: per-key sequential fold, vmapped across the tenant axis.

        Each key scans the whole batch, applies the template update speculatively, and
        commits the result only for its own elements — exact per-instance semantics
        (including op order), at ``O(num_keys x batch)`` compute.
        """
        tpl = self._template
        upd = tpl._update
        names = self._tpl_names

        def per_key(st_n: Dict[str, Array], key: Array) -> Dict[str, Array]:
            def body(st, elem):
                ids_i, (e_args, e_kwargs) = elem
                out = upd(dict(st), *e_args, **e_kwargs)
                hit = ids_i == key
                return {n: jnp.where(hit, out.get(n, st[n]), st[n]) for n in st}, None

            final, _ = jax.lax.scan(body, st_n, (key_ids, (args, kwargs)))
            return final

        sub = {n: state[n] for n in names}
        return jax.vmap(per_key)(sub, jnp.arange(self.num_keys))

    def _compute(self, state: Dict[str, Any]) -> Any:
        """Finalise every stream: the template's compute vmapped over the tenant axis."""
        sub = {n: state[n] for n in self._tpl_names}
        return jax.vmap(self._template._compute)(sub)

    # ------------------------------------------------------------------- protocol
    def _check_key_ids(self, key_ids: Any, args: tuple = (), kwargs: Optional[dict] = None) -> None:
        """Host-side key validation + activity counters (skipped for traced ids)."""
        if not args and not kwargs:
            raise TorchMetricsUserError(
                "KeyedMetric.update needs the template metric's batch inputs after key_ids"
            )
        if is_traced(key_ids):
            return
        ids = np.asarray(key_ids)
        if self.validate_keys:
            if ids.dtype.kind not in "iu":
                raise TorchMetricsUserError(
                    f"key_ids must be an integer array, got dtype {ids.dtype}"
                )
            if ids.size and (ids.min() < 0 or ids.max() >= self.num_keys):
                raise TorchMetricsUserError(
                    f"key_ids out of range: found values in [{ids.min()}, {ids.max()}],"
                    f" this KeyedMetric holds keys [0, {self.num_keys})."
                )
        if ids.size:
            uniq = np.unique(ids)
            obs.telemetry.counter("keyed.fanout").inc(int(uniq.size))
            seen = self._seen_keys
            newly = int(np.count_nonzero(~seen[uniq]))
            if newly:
                seen[uniq] = True
                self._active_count += newly
                obs.telemetry.counter("keyed.active_keys").inc(newly)

    def update(self, key_ids: Any, *args: Any, **kwargs: Any) -> None:
        """Fold one mixed-tenant batch into the tenant table — ONE fused launch.

        ``key_ids`` is an integer array of shape ``[batch]`` (element i belongs to stream
        ``key_ids[i]``); the remaining args/kwargs are the template metric's usual update
        inputs with the same leading batch axis.
        """
        self._check_key_ids(key_ids, args, kwargs)
        obs.telemetry.counter("keyed.updates").inc()
        super().update(key_ids, *args, **kwargs)

    def update_batches(self, key_ids: Any, *args: Any, **kwargs: Any) -> None:
        """Whole-stack sweep: ``key_ids`` and batch args carry an extra leading axis."""
        self._check_key_ids(key_ids, args, kwargs)
        n_batches = jnp.shape(key_ids)[0]
        obs.telemetry.counter("keyed.updates").inc(int(n_batches))
        super().update_batches(key_ids, *args, **kwargs)

    def compute(self, keys: Optional[Any] = None) -> Any:
        """Finalise per-key values.

        ``keys=None`` finalises every stream (shape ``[num_keys, ...]`` per output leaf).
        With ``keys`` (an int sequence/array), only the requested rows of the tenant
        table are gathered and finalised — lazy: cost scales with ``len(keys)``, not
        ``num_keys``. The gather path honours the same sync/guard discipline as a plain
        ``compute()`` (poison guard, buffered-pending guard, ``sync_on_compute``).
        """
        if keys is None:
            return super().compute()
        _dispatch.guard_buffered_pending(self, "compute")
        if self._serve is not None:
            self._serve.quiesce()  # per-key gathers see every async batch too
        obs.bump(self, "compute_calls")
        self._guard_poison()
        keys_arr = jnp.asarray(keys)
        if keys_arr.ndim == 0:
            keys_arr = keys_arr[None]
        if self.validate_keys and not is_traced(keys):
            ids = np.asarray(keys_arr)
            if ids.dtype.kind not in "iu":
                raise TorchMetricsUserError(f"compute(keys=...) needs integer keys, got {ids.dtype}")
            if ids.size and (ids.min() < 0 or ids.max() >= self.num_keys):
                raise TorchMetricsUserError(
                    f"compute(keys=...) out of range: [{ids.min()}, {ids.max()}] vs"
                    f" [0, {self.num_keys})"
                )
        obs.count_dispatch(self)
        with obs.metric_span(self, "compute"):
            with self.sync_context(
                dist_sync_fn=self.dist_sync_fn,
                should_sync=self._to_sync,
                should_unsync=self._should_unsync,
            ):
                fn = self._jit_cache.get("keyed_gather")
                if fn is None:
                    tpl_compute = self._template._compute
                    names = self._tpl_names

                    def gather(state: Dict[str, Array], ks: Array):
                        sub = {n: state[n][ks] for n in names}
                        return jax.vmap(tpl_compute)(sub)

                    fn = jax.jit(obs.instrument_trace(gather, self, "keyed_gather"))
                    self._jit_cache["keyed_gather"] = fn
                value = fn({n: self._state.tensors[n] for n in self._tpl_names}, keys_arr)
        return value

    def compute_key(self, key: int) -> Any:
        """One stream's value (a single-row :meth:`compute` gather, leading axis dropped)."""
        value = self.compute(keys=jnp.asarray([int(key)]))
        return jax.tree_util.tree_map(lambda v: v[0], value)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        raise TorchMetricsUserError(
            "KeyedMetric has no per-batch forward value: a mixed-tenant batch has one"
            " value PER KEY, not per batch. Drive it with update(key_ids, ...) and read"
            " values with compute(keys=...)."
        )

    def reset(self) -> None:
        super().reset()
        self._seen_keys[:] = False
        self._active_count = 0

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}({type(self._template).__name__}(),"
            f" num_keys={self.num_keys}, strategy={self._strategy!r})"
        )


class KeyedMetricCollection(MetricCollection):
    """Many keyed metrics, one ``update(key_ids, ...)`` call, shared tenant axis.

    Accepts the same inputs as :class:`~torchmetrics_tpu.collections.MetricCollection`
    (metric / sequence / dict, or a whole collection) and wraps every member in a
    :class:`KeyedMetric` over the shared ``num_keys``. Already-keyed members pass through
    when their ``num_keys`` matches.

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.aggregation import MaxMetric, SumMetric
        >>> from torchmetrics_tpu.keyed import KeyedMetricCollection
        >>> kc = KeyedMetricCollection([SumMetric(), MaxMetric()], num_keys=3)
        >>> kc.update(np.array([0, 1, 0]), np.array([1.0, 5.0, 2.0]))
        >>> {k: np.asarray(v).tolist() for k, v in sorted(kc.compute(keys=[0, 1]).items())}
        {'MaxMetric': [2.0, 5.0], 'SumMetric': [3.0, 5.0]}
    """

    def __init__(
        self,
        metrics: Union[Metric, MetricCollection, Sequence, Dict[str, Any]],
        *additional_metrics: Metric,
        num_keys: int,
        strategy: str = "auto",
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, list] = True,
        **keyed_kwargs: Any,
    ) -> None:
        self.num_keys = int(num_keys)

        def wrap(m: Any) -> Any:
            if isinstance(m, KeyedMetric):
                if m.num_keys != self.num_keys:
                    raise ValueError(
                        f"KeyedMetricCollection(num_keys={self.num_keys}) cannot hold a"
                        f" KeyedMetric with num_keys={m.num_keys}"
                    )
                return m
            if isinstance(m, MetricCollection):
                return KeyedMetricCollection(
                    dict(m.items(keep_base=True, copy_state=False)),
                    num_keys=self.num_keys, strategy=strategy, **keyed_kwargs,
                )
            return KeyedMetric(m, self.num_keys, strategy=strategy, **keyed_kwargs)

        rest: list = []
        if isinstance(metrics, dict):
            if additional_metrics:
                raise ValueError(
                    f"Received extra positional arguments {additional_metrics} alongside a"
                    f" dict of metrics; name every metric in the dict instead."
                )
            metrics = {name: wrap(m) for name, m in metrics.items()}
        else:
            if isinstance(metrics, Sequence) and not isinstance(metrics, (str, bytes)):
                wrapped = [wrap(m) for m in (*metrics, *additional_metrics)]
            else:
                wrapped = [wrap(metrics), *(wrap(m) for m in additional_metrics)]
            # unnamed members register under the TEMPLATE class name, not "KeyedMetric"
            # N times over; nested collections keep their own member names
            named: Dict[str, Any] = {}
            for w in wrapped:
                if isinstance(w, KeyedMetric):
                    name = type(w.template).__name__
                    if name in named:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    named[name] = w
                else:
                    rest.append(w)
            metrics = named
        super().__init__(metrics, prefix=prefix, postfix=postfix, compute_groups=compute_groups)
        for coll in rest:
            self.add_metrics(coll)

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        raise TorchMetricsUserError(
            "KeyedMetricCollection has no per-batch forward value — use"
            " update(key_ids, ...) + compute(keys=...)."
        )

    def compute(self, keys: Optional[Any] = None) -> Dict[str, Any]:
        """Per-key values for every member; ``keys`` gathers lazily (see ``KeyedMetric.compute``)."""
        if keys is None:
            return super().compute()
        result = {
            name: m.compute(keys=keys)
            for name, m in self.items(keep_base=True, copy_state=False)
        }
        return self._finalize_result(result)
