"""Stateful stat-scores metrics (reference ``src/torchmetrics/classification/stat_scores.py``:
``_AbstractStatScores:40``, ``BinaryStatScores:91``, ``MulticlassStatScores:195``,
``MultilabelStatScores:346``, task wrapper ``StatScores:491``)."""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_compute,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_compute,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multiclass_stat_scores_update,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_compute,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.enums import ClassificationTask


class _AbstractStatScores(Metric):
    """Shared state layout: tensor sum-states for global, cat list-states for samplewise
    (reference ``stat_scores.py:50-88``)."""

    def _create_state(self, size: int, multidim_average: str = "global") -> None:
        if multidim_average == "samplewise":
            default: Any = []
            reduce_fx = "cat"
        else:
            default = jnp.zeros(size, jnp.float32) if size > 1 else jnp.zeros((), jnp.float32)
            reduce_fx = "sum"
        self.add_state("tp", deepcopy_default(default), dist_reduce_fx=reduce_fx)
        self.add_state("fp", deepcopy_default(default), dist_reduce_fx=reduce_fx)
        self.add_state("tn", deepcopy_default(default), dist_reduce_fx=reduce_fx)
        self.add_state("fn", deepcopy_default(default), dist_reduce_fx=reduce_fx)

    def _merge_counts(self, state: Dict[str, Array], tp, fp, tn, fn) -> Dict[str, Array]:
        if self.multidim_average == "samplewise":
            return {"tp": tp, "fp": fp, "tn": tn, "fn": fn}  # appended to list states
        return {
            "tp": state["tp"] + tp,
            "fp": state["fp"] + fp,
            "tn": state["tn"] + tn,
            "fn": state["fn"] + fn,
        }


def deepcopy_default(default):
    return list(default) if isinstance(default, list) else default


class BinaryStatScores(_AbstractStatScores):
    """Reference ``classification/stat_scores.py:91``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        threshold: float = 0.5,
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        self.threshold = threshold
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=1, multidim_average=multidim_average)

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _binary_stat_scores_tensor_validation(preds, target, self.multidim_average, self.ignore_index)

    def _update(self, state, preds, target):
        preds, target, mask = _binary_stat_scores_format(preds, target, self.threshold, self.ignore_index)
        tp, fp, tn, fn = _binary_stat_scores_update(preds, target, mask, self.multidim_average)
        return self._merge_counts(state, tp, fp, tn, fn)

    def _compute(self, state):
        return _binary_stat_scores_compute(state["tp"], state["fp"], state["tn"], state["fn"], self.multidim_average)


class MulticlassStatScores(_AbstractStatScores):
    """Reference ``classification/stat_scores.py:195``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        top_k: int = 1,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        self.num_classes = num_classes
        self.top_k = top_k
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=num_classes, multidim_average=multidim_average)

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(
                preds, target, self.num_classes, self.multidim_average, self.ignore_index, self.top_k
            )

    def _update(self, state, preds, target):
        preds, target = _multiclass_stat_scores_format(preds, target, self.top_k)
        tp, fp, tn, fn = _multiclass_stat_scores_update(
            preds, target, self.num_classes, self.top_k, self.multidim_average, self.ignore_index
        )
        return self._merge_counts(state, tp, fp, tn, fn)

    def _compute(self, state):
        return _multiclass_stat_scores_compute(
            state["tp"], state["fp"], state["tn"], state["fn"], self.average, self.multidim_average
        )


class MultilabelStatScores(_AbstractStatScores):
    """Reference ``classification/stat_scores.py:346``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_labels: int,
        threshold: float = 0.5,
        average: Optional[str] = "macro",
        multidim_average: str = "global",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        self.num_labels = num_labels
        self.threshold = threshold
        self.average = average
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(size=num_labels, multidim_average=multidim_average)

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(
                preds, target, self.num_labels, self.multidim_average, self.ignore_index
            )

    def _update(self, state, preds, target):
        preds, target, mask = _multilabel_stat_scores_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, mask, self.multidim_average)
        return self._merge_counts(state, tp, fp, tn, fn)

    def _compute(self, state):
        return _multilabel_stat_scores_compute(
            state["tp"], state["fp"], state["tn"], state["fn"], self.average, self.multidim_average
        )


class StatScores(_ClassificationTaskWrapper):
    """Task dispatcher: ``StatScores(task="binary"|...)`` (reference ``stat_scores.py:491``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu import StatScores
        >>> metric = StatScores(task='multiclass', num_classes=3, average='micro')
        >>> metric.update(preds, target)
        >>> np.asarray(metric.compute()).tolist()  # [tp, fp, tn, fn, support]
        [3, 1, 7, 1, 4]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "micro",
        multidim_average: str = "global",
        top_k: Optional[int] = 1,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({
            "multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args
        })
        if task == ClassificationTask.BINARY:
            return BinaryStatScores(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassStatScores(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelStatScores(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Task {task} not supported!")
