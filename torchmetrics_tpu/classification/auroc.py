"""Stateful AUROC metrics (reference ``src/torchmetrics/classification/auroc.py:43,168,322,471``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.auroc import (
    _binary_auroc_arg_validation,
    _binary_auroc_compute,
    _multiclass_auroc_arg_validation,
    _multiclass_auroc_compute,
    _multilabel_auroc_arg_validation,
    _multilabel_auroc_compute,
)
from torchmetrics_tpu.functional.classification.precision_recall_curve import Thresholds
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryAUROC(BinaryPrecisionRecallCurve):
    """Reference ``classification/auroc.py:43``.

    Inherits the curve base's state regimes, including ``approx="sketch"``
    (docs/sketches.md): a fixed ``2·sketch_bins``-float streaming histogram pair instead
    of the unbounded exact-mode cat state, |ΔAUROC| bounded by the grid discretisation
    (``sketch.auroc_error_bound``; ~1e-6 measured at the default 2048 bins).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu.classification import BinaryAUROC
        >>> metric = BinaryAUROC()
        >>> metric.update(np.array([0.1, 0.4, 0.35, 0.8], np.float32), np.array([0, 0, 1, 1]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.7500
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        max_fpr: Optional[float] = None,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_auroc_arg_validation(max_fpr, thresholds, ignore_index)
        self.max_fpr = max_fpr
        self.validate_args = validate_args
        if self.max_fpr is not None:
            self.jit_compute = False  # partial-AUC interpolation runs on the host

    def _compute(self, state):
        return _binary_auroc_compute(self._curve_state(state), self.thresholds, self.max_fpr)

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(val, ax=ax, higher_is_better=self.higher_is_better,
                                        name=type(self).__name__, lower_bound=0.0, upper_bound=1.0)


class MulticlassAUROC(MulticlassPrecisionRecallCurve):
    """Reference ``classification/auroc.py:168``.

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu.classification import MulticlassAUROC
        >>> metric = MulticlassAUROC(num_classes=3)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7222
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        # curve state is unaveraged; average applies at compute (micro handled by curve base)
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_auroc_arg_validation(num_classes, average, thresholds, ignore_index)
        self._auroc_average = average  # curve base's self.average stays None (state is per-class)
        self.validate_args = validate_args

    def _compute(self, state):
        return _multiclass_auroc_compute(
            self._curve_state(state), self.num_classes, self._auroc_average, self.thresholds
        )

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(val, ax=ax, higher_is_better=True,
                                        name=type(self).__name__, lower_bound=0.0, upper_bound=1.0)


class MultilabelAUROC(MultilabelPrecisionRecallCurve):
    """Reference ``classification/auroc.py:322``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def __init__(
        self,
        num_labels: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_auroc_arg_validation(num_labels, average, thresholds, ignore_index)
        self.average = average
        self.validate_args = validate_args

    def _compute(self, state):
        return _multilabel_auroc_compute(
            self._curve_state(state), self.num_labels, self.average, self.thresholds, self.ignore_index
        )

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(val, ax=ax, higher_is_better=True,
                                        name=type(self).__name__, lower_bound=0.0, upper_bound=1.0)


class AUROC(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``auroc.py:471``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import AUROC
        >>> metric = AUROC(task='binary')
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7500
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryAUROC(max_fpr, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassAUROC(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelAUROC(num_labels, average, **kwargs)
        raise ValueError(f"Task {task} not supported!")
