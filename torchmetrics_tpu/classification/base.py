"""Task-dispatch wrapper base (reference ``src/torchmetrics/classification/base.py:19``)."""
from __future__ import annotations

from typing import Any

from torchmetrics_tpu.metric import Metric


class _ClassificationTaskWrapper(Metric):
    """Base for wrapper classes like ``Accuracy(task=...)`` whose ``__new__`` returns a task class."""

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise NotImplementedError(
            f"{self.__class__.__name__} metric does not have an `update` method. This is a wrapper class"
            " and you should instead instantiate it with an appropriate task argument."
        )

    def compute(self) -> None:
        raise NotImplementedError(
            f"{self.__class__.__name__} metric does not have a `compute` method. This is a wrapper class"
            " and you should instead instantiate it with an appropriate task argument."
        )
