"""Stateful calibration-error metrics (reference
``src/torchmetrics/classification/calibration_error.py:41,188,342``).

TPU-native state: three ``(n_bins + 1,)`` sum tensors instead of the reference's unbounded
confidence/accuracy lists (binning against the fixed grid commutes with accumulation; the
extra slot holds ``conf == 1.0`` exactly, matching the reference's bucketize indexing)."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.functional.classification.calibration_error import (
    _binary_calibration_error_arg_validation,
    _binary_calibration_error_tensor_validation,
    _binary_confidences_accuracies,
    _binning_bucketize,
    _ce_compute,
    _multiclass_calibration_error_arg_validation,
    _multiclass_calibration_error_tensor_validation,
    _multiclass_confidences_accuracies,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel


class _CalibrationErrorBase(Metric):
    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _init_state(self, n_bins: int) -> None:
        # n_bins + 1 slots: the extra slot holds conf == 1.0 exactly, matching the reference's
        # bucketize(right=True) - 1 indexing over linspace(0, 1, n_bins + 1) boundaries.
        self.add_state("count", jnp.zeros((n_bins + 1,), jnp.float32), dist_reduce_fx="sum")
        self.add_state("conf_sum", jnp.zeros((n_bins + 1,), jnp.float32), dist_reduce_fx="sum")
        self.add_state("acc_sum", jnp.zeros((n_bins + 1,), jnp.float32), dist_reduce_fx="sum")

    def _accumulate(self, state, confidences, accuracies, weight):
        count, conf_sum, acc_sum = _binning_bucketize(confidences, accuracies, weight, self.n_bins)
        return {
            "count": state["count"] + count,
            "conf_sum": state["conf_sum"] + conf_sum,
            "acc_sum": state["acc_sum"] + acc_sum,
        }

    def _compute(self, state):
        return _ce_compute(state["count"], state["conf_sum"], state["acc_sum"], self.norm)


class BinaryCalibrationError(_CalibrationErrorBase):
    """Reference ``classification/calibration_error.py:41``.

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.classification import BinaryCalibrationError
        >>> metric = BinaryCalibrationError(n_bins=2)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.0125
    """

    def __init__(
        self,
        n_bins: int = 15,
        norm: str = "l1",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_state(n_bins)

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _binary_calibration_error_tensor_validation(preds, target, self.ignore_index)

    def _update(self, state, preds, target):
        confidences, accuracies, weight = _binary_confidences_accuracies(preds, target, self.ignore_index)
        return self._accumulate(state, confidences, accuracies, weight)


class MulticlassCalibrationError(_CalibrationErrorBase):
    """Reference ``classification/calibration_error.py:188``."""

    plot_legend_name = "Class"

    def __init__(
        self,
        num_classes: int,
        n_bins: int = 15,
        norm: str = "l1",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        self.num_classes = num_classes
        self.n_bins = n_bins
        self.norm = norm
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._init_state(n_bins)

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multiclass_calibration_error_tensor_validation(preds, target, self.num_classes, self.ignore_index)

    def _update(self, state, preds, target):
        confidences, accuracies, weight = _multiclass_confidences_accuracies(
            preds, target, self.num_classes, self.ignore_index
        )
        return self._accumulate(state, confidences, accuracies, weight)


class CalibrationError(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``calibration_error.py:342``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import CalibrationError
        >>> metric = CalibrationError(task='binary', n_bins=2)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.0125
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        n_bins: int = 15,
        norm: str = "l1",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"n_bins": n_bins, "norm": norm, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryCalibrationError(**kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassCalibrationError(num_classes, **kwargs)
        raise ValueError(f"Task {task} not supported!")
