"""Classification module metrics (reference ``src/torchmetrics/classification/__init__.py``)."""
from torchmetrics_tpu.classification.accuracy import (
    Accuracy,
    BinaryAccuracy,
    MulticlassAccuracy,
    MultilabelAccuracy,
)
from torchmetrics_tpu.classification.cohen_kappa import BinaryCohenKappa, CohenKappa, MulticlassCohenKappa
from torchmetrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    ConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from torchmetrics_tpu.classification.exact_match import ExactMatch, MulticlassExactMatch, MultilabelExactMatch
from torchmetrics_tpu.classification.f_beta import (
    BinaryF1Score,
    BinaryFBetaScore,
    F1Score,
    FBetaScore,
    MulticlassF1Score,
    MulticlassFBetaScore,
    MultilabelF1Score,
    MultilabelFBetaScore,
)
from torchmetrics_tpu.classification.hamming import (
    BinaryHammingDistance,
    HammingDistance,
    MulticlassHammingDistance,
    MultilabelHammingDistance,
)
from torchmetrics_tpu.classification.jaccard import (
    BinaryJaccardIndex,
    JaccardIndex,
    MulticlassJaccardIndex,
    MultilabelJaccardIndex,
)
from torchmetrics_tpu.classification.matthews_corrcoef import (
    BinaryMatthewsCorrCoef,
    MatthewsCorrCoef,
    MulticlassMatthewsCorrCoef,
    MultilabelMatthewsCorrCoef,
)
from torchmetrics_tpu.classification.precision_recall import (
    BinaryPrecision,
    BinaryRecall,
    MulticlassPrecision,
    MulticlassRecall,
    MultilabelPrecision,
    MultilabelRecall,
    Precision,
    Recall,
)
from torchmetrics_tpu.classification.specificity import (
    BinarySpecificity,
    MulticlassSpecificity,
    MultilabelSpecificity,
    Specificity,
)
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
    StatScores,
)
