"""Stateful hinge-loss metrics (reference ``src/torchmetrics/classification/hinge.py:41,170,323``)."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.functional.classification.hinge import (
    _binary_hinge_loss_arg_validation,
    _binary_hinge_loss_tensor_validation,
    _binary_hinge_update,
    _hinge_loss_compute,
    _multiclass_hinge_loss_arg_validation,
    _multiclass_hinge_loss_tensor_validation,
    _multiclass_hinge_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel


class BinaryHingeLoss(Metric):
    """Reference ``classification/hinge.py:41``."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0

    def __init__(
        self,
        squared: bool = False,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_hinge_loss_arg_validation(squared, ignore_index)
        self.squared = squared
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measures", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _binary_hinge_loss_tensor_validation(preds, target, self.ignore_index)

    def _update(self, state, preds, target):
        measures, total = _binary_hinge_update(preds, target, self.squared, self.ignore_index)
        return {"measures": state["measures"] + measures, "total": state["total"] + total}

    def _compute(self, state):
        return _hinge_loss_compute(state["measures"], state["total"])


class MulticlassHingeLoss(Metric):
    """Reference ``classification/hinge.py:170``."""

    is_differentiable = True
    higher_is_better = False
    full_state_update = False
    plot_lower_bound = 0.0
    plot_legend_name = "Class"

    def __init__(
        self,
        num_classes: int,
        squared: bool = False,
        multiclass_mode: str = "crammer-singer",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_hinge_loss_arg_validation(num_classes, squared, multiclass_mode, ignore_index)
        self.num_classes = num_classes
        self.squared = squared
        self.multiclass_mode = multiclass_mode
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        size = () if multiclass_mode == "crammer-singer" else (num_classes,)
        self.add_state("measures", jnp.zeros(size, jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multiclass_hinge_loss_tensor_validation(preds, target, self.num_classes, self.ignore_index)

    def _update(self, state, preds, target):
        measures, total = _multiclass_hinge_update(
            preds, target, self.num_classes, self.squared, self.multiclass_mode, self.ignore_index
        )
        return {"measures": state["measures"] + measures, "total": state["total"] + total}

    def _compute(self, state):
        return _hinge_loss_compute(state["measures"], state["total"])


class HingeLoss(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``hinge.py:323``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu import HingeLoss
        >>> preds = np.array([0.25, 0.25, 0.55, 0.75, 0.75], np.float32)
        >>> target = np.array([0, 0, 1, 1, 1])
        >>> metric = HingeLoss(task='binary')
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.6900
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        num_classes: Optional[int] = None,
        squared: bool = False,
        multiclass_mode: str = "crammer-singer",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryHingeLoss(squared, **kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassHingeLoss(num_classes, squared, multiclass_mode, **kwargs)
        raise ValueError(f"Task {task} not supported!")
