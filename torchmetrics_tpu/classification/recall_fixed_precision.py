"""Stateful recall-at-fixed-precision metrics (reference
``src/torchmetrics/classification/recall_fixed_precision.py:47,177,323,468``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.precision_recall_curve import Thresholds
from torchmetrics_tpu.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _binary_recall_at_fixed_precision_compute,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multiclass_recall_at_fixed_precision_compute,
    _multilabel_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_compute,
)
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryRecallAtFixedPrecision(BinaryPrecisionRecallCurve):
    """Reference ``classification/recall_fixed_precision.py:47``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        self.min_precision = min_precision
        self.validate_args = validate_args

    def _compute(self, state):
        return _binary_recall_at_fixed_precision_compute(
            self._curve_state(state), self.thresholds, self.min_precision
        )


class MulticlassRecallAtFixedPrecision(MulticlassPrecisionRecallCurve):
    """Reference ``classification/recall_fixed_precision.py:177``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_recall_at_fixed_precision_arg_validation(
                num_classes, min_precision, thresholds, ignore_index
            )
        self.min_precision = min_precision
        self.validate_args = validate_args

    def _compute(self, state):
        return _multiclass_recall_at_fixed_precision_compute(
            self._curve_state(state), self.num_classes, self.thresholds, self.min_precision
        )


class MultilabelRecallAtFixedPrecision(MultilabelPrecisionRecallCurve):
    """Reference ``classification/recall_fixed_precision.py:323``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def __init__(
        self,
        num_labels: int,
        min_precision: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_recall_at_fixed_precision_arg_validation(
                num_labels, min_precision, thresholds, ignore_index
            )
        self.min_precision = min_precision
        self.validate_args = validate_args

    def _compute(self, state):
        return _multilabel_recall_at_fixed_precision_compute(
            self._curve_state(state), self.num_labels, self.thresholds, self.ignore_index, self.min_precision
        )


class RecallAtFixedPrecision(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``recall_fixed_precision.py:468``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import RecallAtFixedPrecision
        >>> metric = RecallAtFixedPrecision(task='binary', min_precision=0.5, thresholds=4)
        >>> metric.update(preds, target)
        >>> [round(float(v), 4) for v in metric.compute()]  # (recall, threshold)
        [1.0, 0.3333]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_precision: float,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return BinaryRecallAtFixedPrecision(min_precision, thresholds, ignore_index, validate_args, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassRecallAtFixedPrecision(
                num_classes, min_precision, thresholds, ignore_index, validate_args, **kwargs
            )
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelRecallAtFixedPrecision(
                num_labels, min_precision, thresholds, ignore_index, validate_args, **kwargs
            )
        raise ValueError(f"Task {task} not supported!")
