"""Cohen's kappa metrics (reference ``src/torchmetrics/classification/cohen_kappa.py:35,159,287``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.confusion_matrix import BinaryConfusionMatrix, MulticlassConfusionMatrix
from torchmetrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_reduce, _validate_weights
from torchmetrics_tpu.utils.enums import ClassificationTaskNoMultilabel


class BinaryCohenKappa(BinaryConfusionMatrix):
    """Reference ``cohen_kappa.py:35``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 weights: Optional[str] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(threshold, ignore_index, normalize=None, validate_args=False, **kwargs)
        if validate_args:
            _validate_weights(weights)
        self.weights = weights
        self.validate_args = validate_args

    def _compute(self, state):
        return _cohen_kappa_reduce(state["confmat"], self.weights)

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.metric import Metric

        return Metric.plot(self, val, ax)


class MulticlassCohenKappa(MulticlassConfusionMatrix):
    """Reference ``cohen_kappa.py:159``.

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu.classification import MulticlassCohenKappa
        >>> metric = MulticlassCohenKappa(num_classes=3)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.6364
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, num_classes: int, ignore_index: Optional[int] = None,
                 weights: Optional[str] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes, ignore_index, normalize=None, validate_args=False, **kwargs)
        if validate_args:
            _validate_weights(weights)
        self.weights = weights
        self.validate_args = validate_args

    def _compute(self, state):
        return _cohen_kappa_reduce(state["confmat"], self.weights)

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.metric import Metric

        return Metric.plot(self, val, ax)


class CohenKappa(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``cohen_kappa.py:287``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu import CohenKappa
        >>> metric = CohenKappa(task='multiclass', num_classes=3)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.6364
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
        weights: Optional[str] = None, ignore_index: Optional[int] = None,
        validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTaskNoMultilabel.from_str(task)
        kwargs.update({"weights": weights, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTaskNoMultilabel.BINARY:
            return BinaryCohenKappa(threshold, **kwargs)
        if task == ClassificationTaskNoMultilabel.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassCohenKappa(num_classes, **kwargs)
        raise ValueError(f"Task {task} not supported!")
