"""Stateful group-fairness metrics (reference
``src/torchmetrics/classification/group_fairness.py:59,156``)."""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.group_fairness import (
    _binary_groups_stat_scores_update,
    _compute_binary_demographic_parity,
    _compute_binary_equal_opportunity,
    _groups_validation,
)
from torchmetrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_tensor_validation,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.compute import _safe_divide


class _AbstractGroupStatScores(Metric):
    """Shared (num_groups, 4) [tp, fp, tn, fn] sum state."""

    def _create_states(self, num_groups: int) -> None:
        self.add_state("stats", jnp.zeros((num_groups, 4), jnp.float32), dist_reduce_fx="sum")

    def _validate(self, preds, target, groups) -> None:
        if self.validate_args:
            _binary_stat_scores_tensor_validation(preds, target, "global", self.ignore_index)
            _groups_validation(groups, self.num_groups)

    def _update(self, state, preds, target, groups):
        stats = _binary_groups_stat_scores_update(
            preds, target, groups, self.num_groups, self.threshold, self.ignore_index
        )
        return {"stats": state["stats"] + stats}


class BinaryGroupStatRates(_AbstractGroupStatScores):
    """Per-group tp/fp/tn/fn rates (reference ``group_fairness.py:59``)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        num_groups: int,
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        if not isinstance(num_groups, int) or num_groups < 2:
            raise ValueError(f"Argument `num_groups` must be an int larger than 1, but got {num_groups}")
        self.num_groups = num_groups
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_states(num_groups)

    def _compute(self, state) -> Dict[str, jnp.ndarray]:
        stats = state["stats"]
        return {
            f"group_{g}": _safe_divide(stats[g], jnp.sum(stats[g])) for g in range(self.num_groups)
        }


class BinaryFairness(_AbstractGroupStatScores):
    """Demographic parity / equal opportunity ratios (reference ``group_fairness.py:156``)."""

    is_differentiable = False
    higher_is_better = False
    full_state_update = False
    jit_compute = False  # result keys depend on state values (argmin/argmax group ids)

    def __init__(
        self,
        num_groups: int,
        task: str = "all",
        threshold: float = 0.5,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if task not in ("demographic_parity", "equal_opportunity", "all"):
            raise ValueError(
                f"Expected argument `task` to either be ``demographic_parity``,"
                f"``equal_opportunity`` or ``all`` but got {task}."
            )
        if validate_args:
            _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        if not isinstance(num_groups, int) or num_groups < 2:
            raise ValueError(f"Argument `num_groups` must be an int larger than 1, but got {num_groups}")
        self.num_groups = num_groups
        self.task = task
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_states(num_groups)

    def _validate(self, preds, target, groups) -> None:
        if self.validate_args:
            if self.task != "demographic_parity":
                _binary_stat_scores_tensor_validation(preds, target, "global", self.ignore_index)
            _groups_validation(groups, self.num_groups)

    def _update(self, state, preds, target, groups):
        if self.task == "demographic_parity":
            target = jnp.zeros(jnp.shape(preds), jnp.int32)
        return super()._update(state, preds, target, groups)

    def _compute(self, state) -> Dict[str, jnp.ndarray]:
        stats = state["stats"]
        out: Dict[str, jnp.ndarray] = {}
        if self.task in ("demographic_parity", "all"):
            out.update(_compute_binary_demographic_parity(stats))
        if self.task in ("equal_opportunity", "all"):
            out.update(_compute_binary_equal_opportunity(stats))
        return out
