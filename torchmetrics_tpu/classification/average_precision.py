"""Stateful average-precision metrics (reference
``src/torchmetrics/classification/average_precision.py:46,162,320,476``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.average_precision import (
    _binary_average_precision_compute,
    _multiclass_average_precision_arg_validation,
    _multiclass_average_precision_compute,
    _multilabel_average_precision_arg_validation,
    _multilabel_average_precision_compute,
)
from torchmetrics_tpu.functional.classification.precision_recall_curve import Thresholds
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryAveragePrecision(BinaryPrecisionRecallCurve):
    """Reference ``classification/average_precision.py:46``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _compute(self, state):
        return _binary_average_precision_compute(self._curve_state(state), self.thresholds)

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(val, ax=ax, higher_is_better=True,
                                        name=type(self).__name__, lower_bound=0.0, upper_bound=1.0)


class MulticlassAveragePrecision(MulticlassPrecisionRecallCurve):
    """Reference ``classification/average_precision.py:162``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def __init__(
        self,
        num_classes: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_average_precision_arg_validation(num_classes, average, thresholds, ignore_index)
        self._ap_average = average
        self.validate_args = validate_args

    def _compute(self, state):
        return _multiclass_average_precision_compute(
            self._curve_state(state), self.num_classes, self._ap_average, self.thresholds
        )

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(val, ax=ax, higher_is_better=True,
                                        name=type(self).__name__, lower_bound=0.0, upper_bound=1.0)


class MultilabelAveragePrecision(MultilabelPrecisionRecallCurve):
    """Reference ``classification/average_precision.py:320``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def __init__(
        self,
        num_labels: int,
        average: Optional[str] = "macro",
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_average_precision_arg_validation(num_labels, average, thresholds, ignore_index)
        self.average = average
        self.validate_args = validate_args

    def _compute(self, state):
        return _multilabel_average_precision_compute(
            self._curve_state(state), self.num_labels, self.average, self.thresholds, self.ignore_index
        )

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_single_or_multi_val

        val = val if val is not None else self.compute()
        return plot_single_or_multi_val(val, ax=ax, higher_is_better=True,
                                        name=type(self).__name__, lower_bound=0.0, upper_bound=1.0)


class AveragePrecision(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``average_precision.py:476``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import AveragePrecision
        >>> metric = AveragePrecision(task='binary')
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.8333
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        average: Optional[str] = "macro",
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryAveragePrecision(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassAveragePrecision(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelAveragePrecision(num_labels, average, **kwargs)
        raise ValueError(f"Task {task} not supported!")
