"""Exact-match metrics (reference ``src/torchmetrics/classification/exact_match.py:44,198,367``)."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.functional.classification.exact_match import (
    _exact_match_reduce,
    _multiclass_exact_match_update,
    _multilabel_exact_match_update,
)
from torchmetrics_tpu.functional.classification.stat_scores import (
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.enums import ClassificationTaskNoBinary


class _AbstractExactMatch(Metric):
    def _create_state(self, multidim_average: str) -> None:
        if multidim_average == "samplewise":
            self.add_state("correct", [], dist_reduce_fx="cat")
            self.add_state("total", [], dist_reduce_fx="cat")
        else:
            self.add_state("correct", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
            self.add_state("total", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def _merge(self, state, correct, total):
        if self.multidim_average == "samplewise":
            return {"correct": correct, "total": total}
        return {"correct": state["correct"] + correct, "total": state["total"] + total}

    def _compute(self, state):
        return _exact_match_reduce(state["correct"], state["total"])


class MulticlassExactMatch(_AbstractExactMatch):
    """Reference ``exact_match.py:44``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, num_classes: int, multidim_average: str = "global",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_stat_scores_arg_validation(num_classes, 1, None, multidim_average, ignore_index)
        self.num_classes = num_classes
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(multidim_average)

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multiclass_stat_scores_tensor_validation(
                preds, target, self.num_classes, self.multidim_average, self.ignore_index
            )

    def _update(self, state, preds, target):
        preds, target = _multiclass_stat_scores_format(preds, target, 1)
        correct, total = _multiclass_exact_match_update(preds, target, self.multidim_average, self.ignore_index)
        return self._merge(state, correct, total)


class MultilabelExactMatch(_AbstractExactMatch):
    """Reference ``exact_match.py:198``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, num_labels: int, threshold: float = 0.5, multidim_average: str = "global",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_stat_scores_arg_validation(num_labels, threshold, None, multidim_average, ignore_index)
        self.num_labels = num_labels
        self.threshold = threshold
        self.multidim_average = multidim_average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self._create_state(multidim_average)

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multilabel_stat_scores_tensor_validation(
                preds, target, self.num_labels, self.multidim_average, self.ignore_index
            )

    def _update(self, state, preds, target):
        preds, target, mask = _multilabel_stat_scores_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        correct, total = _multilabel_exact_match_update(preds, target, mask, self.multidim_average)
        return self._merge(state, correct, total)


class ExactMatch(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``exact_match.py:367``).

    Example:
        >>> import numpy as np
        >>> from torchmetrics_tpu import ExactMatch
        >>> metric = ExactMatch(task='multilabel', num_labels=2)
        >>> metric.update(np.array([[0, 1], [1, 1]]), np.array([[0, 1], [0, 1]]))
        >>> print(f"{float(metric.compute()):.4f}")
        0.5000
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
        num_labels: Optional[int] = None, multidim_average: str = "global",
        ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTaskNoBinary.from_str(task)
        kwargs.update({
            "multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args
        })
        if task == ClassificationTaskNoBinary.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassExactMatch(num_classes, **kwargs)
        if task == ClassificationTaskNoBinary.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelExactMatch(num_labels, threshold, **kwargs)
        raise ValueError(f"Task {task} not supported!")
