"""Stateful specificity-at-sensitivity metrics (reference
``src/torchmetrics/classification/specificity_sensitivity.py:46,130,232,330``)."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.precision_recall_curve import Thresholds
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from torchmetrics_tpu.functional.classification.specificity_sensitivity import (
    _specificity_at_sensitivity,
    _val_arg,
)
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinarySpecificityAtSensitivity(BinaryPrecisionRecallCurve):
    """Reference ``classification/specificity_sensitivity.py:46``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        min_sensitivity: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _val_arg(min_sensitivity)
        self.min_sensitivity = min_sensitivity
        self.validate_args = validate_args

    def _compute(self, state):
        fpr, tpr, thr = _binary_roc_compute(self._curve_state(state), self.thresholds)
        return _specificity_at_sensitivity(1 - fpr, tpr, thr, self.min_sensitivity)


class MulticlassSpecificityAtSensitivity(MulticlassPrecisionRecallCurve):
    """Reference ``classification/specificity_sensitivity.py:130``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_legend_name = "Class"

    def __init__(
        self,
        num_classes: int,
        min_sensitivity: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _val_arg(min_sensitivity)
        self.min_sensitivity = min_sensitivity
        self.validate_args = validate_args

    def _compute(self, state):
        fpr, tpr, thr = _multiclass_roc_compute(self._curve_state(state), self.num_classes, self.thresholds)
        if isinstance(fpr, list):
            res = [
                _specificity_at_sensitivity(1 - f, t, h, self.min_sensitivity)
                for f, t, h in zip(fpr, tpr, thr)
            ]
            return jnp.stack([v for v, _ in res]), jnp.stack([h for _, h in res])
        return _specificity_at_sensitivity(1 - fpr, tpr, thr, self.min_sensitivity)


class MultilabelSpecificityAtSensitivity(MultilabelPrecisionRecallCurve):
    """Reference ``classification/specificity_sensitivity.py:232``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_legend_name = "Label"

    def __init__(
        self,
        num_labels: int,
        min_sensitivity: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _val_arg(min_sensitivity)
        self.min_sensitivity = min_sensitivity
        self.validate_args = validate_args

    def _compute(self, state):
        fpr, tpr, thr = _multilabel_roc_compute(
            self._curve_state(state), self.num_labels, self.thresholds, self.ignore_index
        )
        if isinstance(fpr, list):
            res = [
                _specificity_at_sensitivity(1 - f, t, h, self.min_sensitivity)
                for f, t, h in zip(fpr, tpr, thr)
            ]
            return jnp.stack([v for v, _ in res]), jnp.stack([h for _, h in res])
        return _specificity_at_sensitivity(1 - fpr, tpr, thr, self.min_sensitivity)


class SpecificityAtSensitivity(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``specificity_sensitivity.py:330``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import SpecificityAtSensitivity
        >>> metric = SpecificityAtSensitivity(task='binary', min_sensitivity=0.5, thresholds=4)
        >>> metric.update(preds, target)
        >>> [round(float(v), 4) for v in metric.compute()]  # (specificity, threshold)
        [1.0, 0.6667]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_sensitivity: float,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return BinarySpecificityAtSensitivity(min_sensitivity, thresholds, ignore_index, validate_args, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassSpecificityAtSensitivity(
                num_classes, min_sensitivity, thresholds, ignore_index, validate_args, **kwargs
            )
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelSpecificityAtSensitivity(
                num_labels, min_sensitivity, thresholds, ignore_index, validate_args, **kwargs
            )
        raise ValueError(f"Task {task} not supported!")
