"""Stateful Dice metric (reference ``src/torchmetrics/classification/dice.py:31``)."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.dice import (
    _dice_from_counts,
    _dice_update,
    _infer_num_classes,
)
from torchmetrics_tpu.metric import Metric


class Dice(Metric):
    """Dice score = 2·tp / (2·tp + fp + fn) (reference ``dice.py:31``).

    ``average`` ∈ micro/macro/none/samples; ``ignore_index`` drops that class's statistics
    (legacy semantics). ``num_classes`` is required for probabilistic multiclass preds only when
    it cannot be inferred from the class dimension.

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import Dice
        >>> metric = Dice()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7500
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        zero_division: float = 0.0,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: Optional[str] = "micro",
        mdmc_average: Optional[str] = "global",
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_average = ("micro", "macro", "samples", "none", None)
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        if ignore_index is not None and num_classes is not None and not 0 <= ignore_index < num_classes:
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")
        self.zero_division = zero_division
        self.num_classes = num_classes
        self.threshold = threshold
        self.average = average
        self.mdmc_average = mdmc_average
        self.ignore_index = ignore_index
        self.top_k = top_k
        self.multiclass = multiclass
        if multiclass is False and ignore_index is not None:
            raise ValueError("You can not use `ignore_index` with binary data.")
        # Per-sample counts need unbounded cat state: both `average="samples"` and
        # `mdmc_average="samplewise"` reduce within each sample before averaging over samples
        # (reference dice.py:31 mdmc semantics).
        self._samplewise_state = average == "samples" or mdmc_average == "samplewise"
        if self._samplewise_state:
            self.add_state("tp", [], dist_reduce_fx="cat")
            self.add_state("fp", [], dist_reduce_fx="cat")
            self.add_state("fn", [], dist_reduce_fx="cat")
        else:
            n = self._reduced_size()
            self.add_state("tp", jnp.zeros(n, jnp.float32), dist_reduce_fx="sum")
            self.add_state("fp", jnp.zeros(n, jnp.float32), dist_reduce_fx="sum")
            self.add_state("fn", jnp.zeros(n, jnp.float32), dist_reduce_fx="sum")

    def _reduced_size(self) -> int:
        if self.num_classes is None:
            # state allocated lazily on first update is not possible (static shapes); default binary
            return 2 if self.ignore_index is None else 1
        return self.num_classes - (1 if self.ignore_index is not None else 0)

    def _update(self, state, preds, target):
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        if self.multiclass is False:
            from torchmetrics_tpu.functional.classification.dice import _to_binary_for_multiclass_false

            preds, target = _to_binary_for_multiclass_false(preds, target)
        if preds.ndim == target.ndim + 1 and jnp.issubdtype(preds.dtype, jnp.floating):
            n_cls = preds.shape[1]
            if self.num_classes is not None and n_cls != self.num_classes:
                raise ValueError(
                    f"`preds` has {n_cls} classes but metric was built with num_classes={self.num_classes}"
                )
            if self.num_classes is None and not self._samplewise_state and n_cls != self._reduced_size():
                raise ValueError(
                    f"Pass `num_classes={n_cls}` at construction for probabilistic multiclass `preds`"
                    " (state shape must be known up front on TPU)."
                )
            if (self.top_k or 1) == 1:
                preds = jnp.argmax(preds, axis=1)  # top_k > 1 keeps scores for the top-k path
        else:
            n_cls = self.num_classes or 2
        tp, fp, fn = _dice_update(
            preds, target, n_cls, self.threshold, self.top_k, self.ignore_index,
            samplewise=self._samplewise_state,
        )
        if self._samplewise_state:
            return {"tp": tp, "fp": fp, "fn": fn}
        return {"tp": state["tp"] + tp, "fp": state["fp"] + fp, "fn": state["fn"] + fn}

    def _compute(self, state):
        tp, fp, fn = state["tp"], state["fp"], state["fn"]
        if self.multiclass is False:
            # only the positive-class statistics survive the legacy conversion
            tp, fp, fn = tp[..., 1:2], fp[..., 1:2], fn[..., 1:2]
        if self.mdmc_average == "samplewise" and self.average != "samples":
            # per-sample reduction first, then mean over samples (reference mdmc semantics)
            score = _dice_from_counts(tp, fp, fn, self.average, self.zero_division)
            return jnp.mean(score, axis=0)
        return _dice_from_counts(tp, fp, fn, self.average, self.zero_division)
