"""Confusion-matrix metrics (reference ``src/torchmetrics/classification/confusion_matrix.py:51,187,327,470``)."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_compute,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_compute,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_confusion_matrix_compute,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_tensor_validation,
    _multilabel_confusion_matrix_update,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryConfusionMatrix(Metric):
    """Reference ``confusion_matrix.py:51``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 normalize: Optional[str] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((2, 2), jnp.int32), dist_reduce_fx="sum")  # jaxlint: disable=TPU005 — int32 is the TPU-native count dtype (x64 off; int64 would lower to int32), and sample-scale counts stay far below 2^31

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _binary_confusion_matrix_tensor_validation(preds, target, self.ignore_index)

    def _update(self, state, preds, target):
        preds, target = _binary_confusion_matrix_format(preds, target, self.threshold, self.ignore_index)
        return {"confmat": state["confmat"] + _binary_confusion_matrix_update(preds, target)}

    def _compute(self, state):
        return _binary_confusion_matrix_compute(state["confmat"], self.normalize)

    def plot(self, val=None, ax=None, add_text=True, labels=None, cmap=None):
        from torchmetrics_tpu.utils.plot import plot_confusion_matrix

        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax, add_text=add_text, labels=labels, cmap=cmap)


class MulticlassConfusionMatrix(Metric):
    """Reference ``confusion_matrix.py:187``.

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu.classification import MulticlassConfusionMatrix
        >>> metric = MulticlassConfusionMatrix(num_classes=3)
        >>> metric.update(preds, target)
        >>> np.asarray(metric.compute()).tolist()
        [[1, 1, 0], [0, 1, 0], [0, 0, 1]]
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, num_classes: int, ignore_index: Optional[int] = None,
                 normalize: Optional[str] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize)
        self.num_classes = num_classes
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((num_classes, num_classes), jnp.int32), dist_reduce_fx="sum")  # jaxlint: disable=TPU005 — int32 is the TPU-native count dtype (x64 off), sample-scale counts stay far below 2^31

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multiclass_confusion_matrix_tensor_validation(preds, target, self.num_classes, self.ignore_index)

    def _update(self, state, preds, target):
        preds, target = _multiclass_confusion_matrix_format(preds, target, self.ignore_index)
        return {"confmat": state["confmat"] + _multiclass_confusion_matrix_update(preds, target, self.num_classes)}

    def _compute(self, state):
        return _multiclass_confusion_matrix_compute(state["confmat"], self.normalize)

    def plot(self, val=None, ax=None, add_text=True, labels=None, cmap=None):
        from torchmetrics_tpu.utils.plot import plot_confusion_matrix

        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax, add_text=add_text, labels=labels, cmap=cmap)


class MultilabelConfusionMatrix(Metric):
    """Reference ``confusion_matrix.py:327``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(self, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 normalize: Optional[str] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize)
        self.num_labels = num_labels
        self.threshold = threshold
        self.ignore_index = ignore_index
        self.normalize = normalize
        self.validate_args = validate_args
        self.add_state("confmat", jnp.zeros((num_labels, 2, 2), jnp.int32), dist_reduce_fx="sum")  # jaxlint: disable=TPU005 — int32 is the TPU-native count dtype (x64 off), sample-scale counts stay far below 2^31

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multilabel_confusion_matrix_tensor_validation(preds, target, self.num_labels, self.ignore_index)

    def _update(self, state, preds, target):
        preds, target = _multilabel_confusion_matrix_format(
            preds, target, self.num_labels, self.threshold, self.ignore_index
        )
        return {"confmat": state["confmat"] + _multilabel_confusion_matrix_update(preds, target, self.num_labels)}

    def _compute(self, state):
        return _multilabel_confusion_matrix_compute(state["confmat"], self.normalize)

    def plot(self, val=None, ax=None, add_text=True, labels=None, cmap=None):
        from torchmetrics_tpu.utils.plot import plot_confusion_matrix

        val = val if val is not None else self.compute()
        return plot_confusion_matrix(val, ax=ax, add_text=add_text, labels=labels, cmap=cmap)


class ConfusionMatrix(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``confusion_matrix.py:470``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu import ConfusionMatrix
        >>> metric = ConfusionMatrix(task='multiclass', num_classes=3)
        >>> metric.update(preds, target)
        >>> np.asarray(metric.compute()).tolist()
        [[1, 1, 0], [0, 1, 0], [0, 0, 1]]
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
        num_labels: Optional[int] = None, normalize: Optional[str] = None,
        ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({"normalize": normalize, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryConfusionMatrix(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassConfusionMatrix(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelConfusionMatrix(num_labels, threshold, **kwargs)
        raise ValueError(f"Task {task} not supported!")
