"""Jaccard index metrics (reference ``src/torchmetrics/classification/jaccard.py:39,152,282,417``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from torchmetrics_tpu.functional.classification.jaccard import _jaccard_index_reduce
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryJaccardIndex(BinaryConfusionMatrix):
    """Reference ``jaccard.py:39``.

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.classification import BinaryJaccardIndex
        >>> metric = BinaryJaccardIndex()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.5000
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(threshold=threshold, ignore_index=ignore_index, normalize=None,
                         validate_args=validate_args, **kwargs)

    def _compute(self, state):
        return _jaccard_index_reduce(state["confmat"], average="binary")

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.metric import Metric

        return Metric.plot(self, val, ax)


class MulticlassJaccardIndex(MulticlassConfusionMatrix):
    """Reference ``jaccard.py:152``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def __init__(self, num_classes: int, average: Optional[str] = "macro", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, ignore_index=ignore_index, normalize=None,
                         validate_args=validate_args, **kwargs)
        self.average = average

    def _compute(self, state):
        return _jaccard_index_reduce(state["confmat"], average=self.average, ignore_index=self.ignore_index)

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.metric import Metric

        return Metric.plot(self, val, ax)


class MultilabelJaccardIndex(MultilabelConfusionMatrix):
    """Reference ``jaccard.py:282``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def __init__(self, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_labels=num_labels, threshold=threshold, ignore_index=ignore_index,
                         normalize=None, validate_args=validate_args, **kwargs)
        self.average = average

    def _compute(self, state):
        return _jaccard_index_reduce(state["confmat"], average=self.average)

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.metric import Metric

        return Metric.plot(self, val, ax)


class JaccardIndex(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``jaccard.py:417``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu import JaccardIndex
        >>> metric = JaccardIndex(task='multiclass', num_classes=3)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.6667
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
        num_labels: Optional[int] = None, average: Optional[str] = "macro",
        ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryJaccardIndex(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassJaccardIndex(num_classes, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelJaccardIndex(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Task {task} not supported!")
