"""F-beta / F1 metrics (reference ``src/torchmetrics/classification/f_beta.py``:
classes at ``:43,189,371,551,686,858,1026,1090``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.functional.classification.f_beta import _fbeta_reduce, _validate_beta
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryFBetaScore(BinaryStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(self, beta: float, threshold: float = 0.5, multidim_average: str = "global",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(threshold=threshold, multidim_average=multidim_average, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _validate_beta(beta)
        self.validate_args = validate_args
        self.beta = beta

    def _compute(self, state):
        return _fbeta_reduce(state["tp"], state["fp"], state["tn"], state["fn"], self.beta,
                             average="binary", multidim_average=self.multidim_average)


class MulticlassFBetaScore(MulticlassStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def __init__(self, beta: float, num_classes: int, top_k: int = 1, average: Optional[str] = "macro",
                 multidim_average: str = "global", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes=num_classes, top_k=top_k, average=average,
                         multidim_average=multidim_average, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _validate_beta(beta)
        self.validate_args = validate_args
        self.beta = beta

    def _compute(self, state):
        return _fbeta_reduce(state["tp"], state["fp"], state["tn"], state["fn"], self.beta,
                             average=self.average, multidim_average=self.multidim_average, top_k=self.top_k)


class MultilabelFBetaScore(MultilabelStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def __init__(self, beta: float, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                 multidim_average: str = "global", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_labels=num_labels, threshold=threshold, average=average,
                         multidim_average=multidim_average, ignore_index=ignore_index,
                         validate_args=False, **kwargs)
        if validate_args:
            _validate_beta(beta)
        self.validate_args = validate_args
        self.beta = beta

    def _compute(self, state):
        return _fbeta_reduce(state["tp"], state["fp"], state["tn"], state["fn"], self.beta,
                             average=self.average, multidim_average=self.multidim_average, multilabel=True)


class BinaryF1Score(BinaryFBetaScore):
    """Reference ``f_beta.py:551``.

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.classification import BinaryF1Score
        >>> metric = BinaryF1Score()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.6667
    """

    def __init__(self, threshold: float = 0.5, multidim_average: str = "global",
                 ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(1.0, threshold, multidim_average, ignore_index, validate_args, **kwargs)


class MulticlassF1Score(MulticlassFBetaScore):
    """Reference ``f_beta.py:686``.

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu.classification import MulticlassF1Score
        >>> metric = MulticlassF1Score(num_classes=3)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7778
    """

    def __init__(self, num_classes: int, top_k: int = 1, average: Optional[str] = "macro",
                 multidim_average: str = "global", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(1.0, num_classes, top_k, average, multidim_average, ignore_index, validate_args, **kwargs)


class MultilabelF1Score(MultilabelFBetaScore):
    """Reference ``f_beta.py:858``."""

    def __init__(self, num_labels: int, threshold: float = 0.5, average: Optional[str] = "macro",
                 multidim_average: str = "global", ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(1.0, num_labels, threshold, average, multidim_average, ignore_index, validate_args, **kwargs)


class FBetaScore(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``f_beta.py:1026``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu import FBetaScore
        >>> metric = FBetaScore(task='multiclass', num_classes=3, beta=0.5)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7500
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, beta: float = 1.0, threshold: float = 0.5, num_classes: Optional[int] = None,
        num_labels: Optional[int] = None, average: Optional[str] = "micro", multidim_average: str = "global",
        top_k: Optional[int] = 1, ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({
            "multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args
        })
        if task == ClassificationTask.BINARY:
            return BinaryFBetaScore(beta, threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassFBetaScore(beta, num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelFBetaScore(beta, num_labels, threshold, average, **kwargs)
        raise ValueError(f"Task {task} not supported!")


class F1Score(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``f_beta.py:1090``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu import F1Score
        >>> metric = F1Score(task='multiclass', num_classes=3)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7500
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
        num_labels: Optional[int] = None, average: Optional[str] = "micro", multidim_average: str = "global",
        top_k: Optional[int] = 1, ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({
            "multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args
        })
        if task == ClassificationTask.BINARY:
            return BinaryF1Score(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassF1Score(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelF1Score(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Task {task} not supported!")
