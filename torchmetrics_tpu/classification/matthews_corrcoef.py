"""Matthews corrcoef metrics (reference ``src/torchmetrics/classification/matthews_corrcoef.py:39,147,259,370``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.confusion_matrix import (
    BinaryConfusionMatrix,
    MulticlassConfusionMatrix,
    MultilabelConfusionMatrix,
)
from torchmetrics_tpu.functional.classification.matthews_corrcoef import _matthews_corrcoef_reduce
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryMatthewsCorrCoef(BinaryConfusionMatrix):
    """Reference ``matthews_corrcoef.py:39``.

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.classification import BinaryMatthewsCorrCoef
        >>> metric = BinaryMatthewsCorrCoef()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.5774
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(threshold, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def _compute(self, state):
        return _matthews_corrcoef_reduce(state["confmat"])

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.metric import Metric

        return Metric.plot(self, val, ax)


class MulticlassMatthewsCorrCoef(MulticlassConfusionMatrix):
    """Reference ``matthews_corrcoef.py:147``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, num_classes: int, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_classes, ignore_index, normalize=None, validate_args=validate_args, **kwargs)

    def _compute(self, state):
        return _matthews_corrcoef_reduce(state["confmat"])

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.metric import Metric

        return Metric.plot(self, val, ax)


class MultilabelMatthewsCorrCoef(MultilabelConfusionMatrix):
    """Reference ``matthews_corrcoef.py:259``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = -1.0
    plot_upper_bound = 1.0

    def __init__(self, num_labels: int, threshold: float = 0.5, ignore_index: Optional[int] = None,
                 validate_args: bool = True, **kwargs: Any) -> None:
        super().__init__(num_labels, threshold, ignore_index, normalize=None,
                         validate_args=validate_args, **kwargs)

    def _compute(self, state):
        return _matthews_corrcoef_reduce(state["confmat"])

    def plot(self, val=None, ax=None):
        from torchmetrics_tpu.metric import Metric

        return Metric.plot(self, val, ax)


class MatthewsCorrCoef(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``matthews_corrcoef.py:370``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu import MatthewsCorrCoef
        >>> metric = MatthewsCorrCoef(task='multiclass', num_classes=3)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7000
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
        num_labels: Optional[int] = None, ignore_index: Optional[int] = None,
        validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({"ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryMatthewsCorrCoef(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassMatthewsCorrCoef(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelMatthewsCorrCoef(num_labels, threshold, **kwargs)
        raise ValueError(f"Task {task} not supported!")
