"""Stateful multilabel ranking metrics (reference
``src/torchmetrics/classification/ranking.py:40,160,280``)."""
from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from torchmetrics_tpu.functional.classification.ranking import (
    _format,
    _multilabel_coverage_error_update,
    _multilabel_ranking_arg_validation,
    _multilabel_ranking_average_precision_update,
    _multilabel_ranking_loss_update,
    _multilabel_ranking_tensor_validation,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.utils.compute import _safe_divide


class _RankingBase(Metric):
    is_differentiable = False
    full_state_update = False

    _update_fn = None  # set by subclass

    def __init__(
        self,
        num_labels: int,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if validate_args:
            _multilabel_ranking_arg_validation(num_labels, ignore_index)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.add_state("measure", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multilabel_ranking_tensor_validation(preds, target, self.num_labels, self.ignore_index)

    def _update(self, state, preds, target):
        preds, target, weight = _format(preds, target, self.num_labels, self.ignore_index)
        measure, n = type(self)._update_fn(preds, target, weight)
        return {"measure": state["measure"] + measure, "total": state["total"] + n}

    def _compute(self, state):
        return _safe_divide(state["measure"], state["total"])


class MultilabelCoverageError(_RankingBase):
    """Reference ``classification/ranking.py:40``."""

    higher_is_better = False
    _update_fn = staticmethod(_multilabel_coverage_error_update)


class MultilabelRankingAveragePrecision(_RankingBase):
    """Reference ``classification/ranking.py:160``."""

    higher_is_better = True
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    _update_fn = staticmethod(_multilabel_ranking_average_precision_update)


class MultilabelRankingLoss(_RankingBase):
    """Reference ``classification/ranking.py:280``."""

    higher_is_better = False
    plot_lower_bound = 0.0
    _update_fn = staticmethod(_multilabel_ranking_loss_update)
