"""Stateful precision-at-fixed-recall metrics (reference
``src/torchmetrics/classification/precision_fixed_recall.py:48,180,324,469``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.precision_fixed_recall import (
    _precision_at_recall,
)
from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _binary_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_compute,
)
from torchmetrics_tpu.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_arg_validation,
)
from torchmetrics_tpu.utils.enums import ClassificationTask

import jax.numpy as jnp


class BinaryPrecisionAtFixedRecall(BinaryPrecisionRecallCurve):
    """Reference ``classification/precision_fixed_recall.py:48``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def __init__(
        self,
        min_recall: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(thresholds=thresholds, ignore_index=ignore_index, validate_args=False, **kwargs)
        if validate_args:
            _binary_recall_at_fixed_precision_arg_validation(min_recall, thresholds, ignore_index)
        self.min_recall = min_recall
        self.validate_args = validate_args

    def _compute(self, state):
        p, r, t = _binary_precision_recall_curve_compute(self._curve_state(state), self.thresholds)
        return _precision_at_recall(p, r, t, self.min_recall)


class MulticlassPrecisionAtFixedRecall(MulticlassPrecisionRecallCurve):
    """Reference ``classification/precision_fixed_recall.py:180``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def __init__(
        self,
        num_classes: int,
        min_recall: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_recall, thresholds, ignore_index)
        self.min_recall = min_recall
        self.validate_args = validate_args

    def _compute(self, state):
        p, r, t = _multiclass_precision_recall_curve_compute(
            self._curve_state(state), self.num_classes, self.thresholds
        )
        if isinstance(p, list):
            res = [_precision_at_recall(pc, rc, tc, self.min_recall) for pc, rc, tc in zip(p, r, t)]
            return jnp.stack([v for v, _ in res]), jnp.stack([thr for _, thr in res])
        thr = jnp.broadcast_to(t, (p.shape[0], t.shape[0]))
        return _precision_at_recall(p, r, thr, self.min_recall)


class MultilabelPrecisionAtFixedRecall(MultilabelPrecisionRecallCurve):
    """Reference ``classification/precision_fixed_recall.py:324``."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def __init__(
        self,
        num_labels: int,
        min_recall: float,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_labels=num_labels, thresholds=thresholds, ignore_index=ignore_index,
            validate_args=False, **kwargs,
        )
        if validate_args:
            _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_recall, thresholds, ignore_index)
        self.min_recall = min_recall
        self.validate_args = validate_args

    def _compute(self, state):
        p, r, t = _multilabel_precision_recall_curve_compute(
            self._curve_state(state), self.num_labels, self.thresholds, self.ignore_index
        )
        if isinstance(p, list):
            res = [_precision_at_recall(pc, rc, tc, self.min_recall) for pc, rc, tc in zip(p, r, t)]
            return jnp.stack([v for v, _ in res]), jnp.stack([thr for _, thr in res])
        thr = jnp.broadcast_to(t, (p.shape[0], t.shape[0]))
        return _precision_at_recall(p, r, thr, self.min_recall)


class PrecisionAtFixedRecall(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``precision_fixed_recall.py:469``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import PrecisionAtFixedRecall
        >>> metric = PrecisionAtFixedRecall(task='binary', min_recall=0.5, thresholds=4)
        >>> metric.update(preds, target)
        >>> [round(float(v), 4) for v in metric.compute()]  # (precision, threshold)
        [1.0, 0.6667]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        min_recall: float,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        if task == ClassificationTask.BINARY:
            return BinaryPrecisionAtFixedRecall(min_recall, thresholds, ignore_index, validate_args, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassPrecisionAtFixedRecall(
                num_classes, min_recall, thresholds, ignore_index, validate_args, **kwargs
            )
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelPrecisionAtFixedRecall(
                num_labels, min_recall, thresholds, ignore_index, validate_args, **kwargs
            )
        raise ValueError(f"Task {task} not supported!")
