"""Precision / Recall metrics (reference ``src/torchmetrics/classification/precision_recall.py``:
classes at ``:38,160,316,469,591,746,898,961``)."""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.stat_scores import (
    BinaryStatScores,
    MulticlassStatScores,
    MultilabelStatScores,
)
from torchmetrics_tpu.functional.classification.precision_recall import _precision_recall_reduce
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryPrecision(BinaryStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _compute(self, state):
        return _precision_recall_reduce(
            "precision", state["tp"], state["fp"], state["tn"], state["fn"], average="binary",
            multidim_average=self.multidim_average,
        )


class MulticlassPrecision(MulticlassStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def _compute(self, state):
        return _precision_recall_reduce(
            "precision", state["tp"], state["fp"], state["tn"], state["fn"], average=self.average,
            multidim_average=self.multidim_average, top_k=self.top_k,
        )


class MultilabelPrecision(MultilabelStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def _compute(self, state):
        return _precision_recall_reduce(
            "precision", state["tp"], state["fp"], state["tn"], state["fn"], average=self.average,
            multidim_average=self.multidim_average, multilabel=True,
        )


class BinaryRecall(BinaryStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0

    def _compute(self, state):
        return _precision_recall_reduce(
            "recall", state["tp"], state["fp"], state["tn"], state["fn"], average="binary",
            multidim_average=self.multidim_average,
        )


class MulticlassRecall(MulticlassStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Class"

    def _compute(self, state):
        return _precision_recall_reduce(
            "recall", state["tp"], state["fp"], state["tn"], state["fn"], average=self.average,
            multidim_average=self.multidim_average, top_k=self.top_k,
        )


class MultilabelRecall(MultilabelStatScores):
    is_differentiable = False
    higher_is_better = True
    full_state_update = False
    plot_lower_bound = 0.0
    plot_upper_bound = 1.0
    plot_legend_name = "Label"

    def _compute(self, state):
        return _precision_recall_reduce(
            "recall", state["tp"], state["fp"], state["tn"], state["fn"], average=self.average,
            multidim_average=self.multidim_average, multilabel=True,
        )


class Precision(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``precision_recall.py:898``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu.classification import BinaryPrecision
        >>> metric = BinaryPrecision()
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        1.0000
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
        num_labels: Optional[int] = None, average: Optional[str] = "micro", multidim_average: str = "global",
        top_k: Optional[int] = 1, ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({
            "multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args
        })
        if task == ClassificationTask.BINARY:
            return BinaryPrecision(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassPrecision(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelPrecision(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Task {task} not supported!")


class Recall(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``precision_recall.py:961``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([[0.16, 0.26, 0.58], [0.22, 0.61, 0.17],
        ...                   [0.71, 0.09, 0.20], [0.05, 0.82, 0.13]], np.float32)
        >>> target = np.array([2, 1, 0, 0])
        >>> from torchmetrics_tpu import Recall
        >>> metric = Recall(task='multiclass', num_classes=3)
        >>> metric.update(preds, target)
        >>> print(f"{float(metric.compute()):.4f}")
        0.7500
    """

    def __new__(  # type: ignore[misc]
        cls, task: str, threshold: float = 0.5, num_classes: Optional[int] = None,
        num_labels: Optional[int] = None, average: Optional[str] = "micro", multidim_average: str = "global",
        top_k: Optional[int] = 1, ignore_index: Optional[int] = None, validate_args: bool = True, **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({
            "multidim_average": multidim_average, "ignore_index": ignore_index, "validate_args": validate_args
        })
        if task == ClassificationTask.BINARY:
            return BinaryRecall(threshold, **kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            if not isinstance(top_k, int):
                raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
            return MulticlassRecall(num_classes, top_k, average, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelRecall(num_labels, threshold, average, **kwargs)
        raise ValueError(f"Task {task} not supported!")
