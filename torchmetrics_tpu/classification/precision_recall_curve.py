"""Stateful precision-recall-curve metrics (reference
``src/torchmetrics/classification/precision_recall_curve.py:55,226,424,616``).

State regimes (reference ``:190-250`` translated TPU-first):

- ``thresholds=None`` (exact): unbounded ``cat`` list states of formatted scores; compute runs on
  the host path (sklearn semantics) — ``jit_compute`` is disabled.
- ``thresholds=int|list|array`` (binned, the TPU-native default style): one fixed-shape
  ``(T, ..., 2, 2)`` confusion tensor in HBM with ``dist_reduce_fx="sum"`` — sync is a single
  psum, update is O(N+T) bucketed histograms.
- ``approx="sketch"`` (streaming sketch, docs/sketches.md): a ``(..., sketch_bins)``
  positive/negative threshold-histogram PAIR (``torchmetrics_tpu.sketch.hist``) — 4x
  smaller than the binned confusion tensor, updated with ONE fused weighted-bincount
  launch, merged by sum everywhere (fused forward ladder, keyed segment reductions,
  ``shard()``, quorum sync). Equivalent to binned mode over the implicit
  ``linspace(0, 1, sketch_bins)`` grid; vs EXACT mode the error is the grid
  discretisation (documented bound ``sketch.auroc_error_bound(sketch_bins)``).
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    Thresholds,
    _adjust_threshold_arg,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from torchmetrics_tpu.functional.classification.precision_recall_curve import (
    _counts_to_confmat,
)
from torchmetrics_tpu.metric import Metric
from torchmetrics_tpu.sketch import hist as _sketch_hist
from torchmetrics_tpu.sketch.state import hist_spec, register_sketch_state
from torchmetrics_tpu.utils.enums import ClassificationTask


def _validate_approx(approx: Optional[str], thresholds: Any) -> None:
    """Shared ``approx`` argument contract for the whole curve family."""
    if approx not in (None, "sketch"):
        raise ValueError(f"Argument `approx` must be None or 'sketch', got {approx!r}")
    if approx == "sketch" and thresholds is not None:
        raise ValueError(
            "approx='sketch' replaces the threshold grid with its own `sketch_bins`-wide"
            " implicit uniform grid — pass thresholds=None (exact-mode signature), or use"
            " plain binned mode (thresholds=int) without approx."
        )


class BinaryPrecisionRecallCurve(Metric):
    """Reference ``classification/precision_recall_curve.py:55``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        approx: Optional[str] = None,
        sketch_bins: int = _sketch_hist.DEFAULT_BINS,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _validate_approx(approx, thresholds)
        if validate_args:
            _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.approx = approx
        self.sketch_bins = int(sketch_bins)
        if approx == "sketch":
            # sketch mode ≡ binned mode over the implicit uniform grid: every inherited
            # compute (ROC, AUROC, AP, fixed-recall/precision) sees a plain threshold
            # array + confmat, but the resident state is the 2·bins histogram pair
            self.thresholds = _adjust_threshold_arg(self.sketch_bins)
            register_sketch_state(self, "pos_hist", hist_spec(bins=self.sketch_bins))
            register_sketch_state(self, "neg_hist", hist_spec(bins=self.sketch_bins))
            return
        thresholds = _adjust_threshold_arg(thresholds)
        self.thresholds = thresholds
        if thresholds is None:
            self.jit_compute = False  # exact mode finalises on the host (dynamic shapes)
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
            self.add_state("weight", [], dist_reduce_fx="cat")
        else:
            t = thresholds.shape[0]
            self.add_state("confmat", jnp.zeros((t, 2, 2), jnp.float32), dist_reduce_fx="sum")

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _binary_precision_recall_curve_tensor_validation(preds, target, self.ignore_index)

    def _update(self, state, preds, target):
        preds, target, weight, _ = _binary_precision_recall_curve_format(
            preds, target, None, self.ignore_index
        )
        if self.approx == "sketch":
            pos_hist, neg_hist = _sketch_hist.hist_update_pair(
                state["pos_hist"], state["neg_hist"], preds,
                weight * target.astype(jnp.float32),
                weight * (1.0 - target.astype(jnp.float32)),
            )
            return {"pos_hist": pos_hist, "neg_hist": neg_hist}
        if self.thresholds is None:
            return {"preds": preds, "target": target, "weight": weight}
        return {
            "confmat": state["confmat"]
            + _binary_precision_recall_curve_update(preds, target, weight, self.thresholds)
        }

    def _curve_state(self, state):
        if self.approx == "sketch":
            tp, fp, tn, fn = _sketch_hist.hist_threshold_counts(
                state["pos_hist"], state["neg_hist"]
            )
            return _counts_to_confmat(tp, fp, tn, fn)  # (T, 2, 2)
        if self.thresholds is None:
            return (state["preds"], state["target"], state["weight"])
        return state["confmat"]

    def _compute(self, state) -> Tuple[Array, Array, Array]:
        return _binary_precision_recall_curve_compute(self._curve_state(state), self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        """Plot the (or a provided) curve (reference ``precision_recall_curve.py:160``)."""
        from torchmetrics_tpu.utils.plot import plot_curve

        curve = curve or self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("Recall", "Precision"))


class MulticlassPrecisionRecallCurve(Metric):
    """Reference ``classification/precision_recall_curve.py:226``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        thresholds: Thresholds = None,
        average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        approx: Optional[str] = None,
        sketch_bins: int = _sketch_hist.DEFAULT_BINS,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _validate_approx(approx, thresholds)
        if validate_args:
            _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index, average)
        self.num_classes = num_classes
        self.average = average
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.approx = approx
        self.sketch_bins = int(sketch_bins)
        if approx == "sketch":
            self.thresholds = _adjust_threshold_arg(self.sketch_bins)
            classes = None if average == "micro" else num_classes
            register_sketch_state(self, "pos_hist", hist_spec(bins=self.sketch_bins, classes=classes))
            register_sketch_state(self, "neg_hist", hist_spec(bins=self.sketch_bins, classes=classes))
            return
        thresholds = _adjust_threshold_arg(thresholds)
        self.thresholds = thresholds
        if thresholds is None:
            self.jit_compute = False
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
            self.add_state("weight", [], dist_reduce_fx="cat")
        else:
            t = thresholds.shape[0]
            shape = (t, 2, 2) if average == "micro" else (t, num_classes, 2, 2)
            self.add_state("confmat", jnp.zeros(shape, jnp.float32), dist_reduce_fx="sum")

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multiclass_precision_recall_curve_tensor_validation(
                preds, target, self.num_classes, self.ignore_index
            )

    def _update(self, state, preds, target):
        preds, target, weight, _ = _multiclass_precision_recall_curve_format(
            preds, target, self.num_classes, None, self.ignore_index, self.average
        )
        if self.approx == "sketch":
            if self.average == "micro":  # one-vs-rest flattened: binary histogram pair
                pos_hist, neg_hist = _sketch_hist.hist_update_pair(
                    state["pos_hist"], state["neg_hist"], preds,
                    weight * target.astype(jnp.float32),
                    weight * (1.0 - target.astype(jnp.float32)),
                )
            else:
                pos = (target[:, None] == jnp.arange(self.num_classes)[None, :]).astype(jnp.float32)
                w = weight[:, None]
                pos_hist, neg_hist = _sketch_hist.hist_update_classes(
                    state["pos_hist"], state["neg_hist"], preds, pos * w, (1.0 - pos) * w
                )
            return {"pos_hist": pos_hist, "neg_hist": neg_hist}
        if self.thresholds is None:
            return {"preds": preds, "target": target, "weight": weight}
        if self.average == "micro":
            update = _binary_precision_recall_curve_update(preds, target, weight, self.thresholds)
        else:
            update = _multiclass_precision_recall_curve_update(
                preds, target, weight, self.num_classes, self.thresholds
            )
        return {"confmat": state["confmat"] + update}

    def _curve_state(self, state):
        if self.approx == "sketch":
            tp, fp, tn, fn = _sketch_hist.hist_threshold_counts(
                state["pos_hist"], state["neg_hist"]
            )
            if self.average == "micro":
                return _counts_to_confmat(tp, fp, tn, fn)  # (T, 2, 2)
            return _counts_to_confmat(tp.T, fp.T, tn.T, fn.T)  # (T, C, 2, 2)
        if self.thresholds is None:
            return (state["preds"], state["target"], state["weight"])
        return state["confmat"]

    def _compute(self, state):
        return _multiclass_precision_recall_curve_compute(
            self._curve_state(state), self.num_classes, self.thresholds, self.average
        )

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_curve

        curve = curve or self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("Recall", "Precision"))


class MultilabelPrecisionRecallCurve(Metric):
    """Reference ``classification/precision_recall_curve.py:424``."""

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_labels: int,
        thresholds: Thresholds = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        approx: Optional[str] = None,
        sketch_bins: int = _sketch_hist.DEFAULT_BINS,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        _validate_approx(approx, thresholds)
        if validate_args:
            _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        self.num_labels = num_labels
        self.ignore_index = ignore_index
        self.validate_args = validate_args
        self.approx = approx
        self.sketch_bins = int(sketch_bins)
        if approx == "sketch":
            self.thresholds = _adjust_threshold_arg(self.sketch_bins)
            register_sketch_state(self, "pos_hist", hist_spec(bins=self.sketch_bins, classes=num_labels))
            register_sketch_state(self, "neg_hist", hist_spec(bins=self.sketch_bins, classes=num_labels))
            return
        thresholds = _adjust_threshold_arg(thresholds)
        self.thresholds = thresholds
        if thresholds is None:
            self.jit_compute = False
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")
            self.add_state("weight", [], dist_reduce_fx="cat")
        else:
            t = thresholds.shape[0]
            self.add_state("confmat", jnp.zeros((t, num_labels, 2, 2), jnp.float32), dist_reduce_fx="sum")

    def _validate(self, preds, target) -> None:
        if self.validate_args:
            _multilabel_precision_recall_curve_tensor_validation(
                preds, target, self.num_labels, self.ignore_index
            )

    def _update(self, state, preds, target):
        preds, target, weight, _ = _multilabel_precision_recall_curve_format(
            preds, target, self.num_labels, None, self.ignore_index
        )
        if self.approx == "sketch":
            t01 = target.astype(jnp.float32)
            pos_hist, neg_hist = _sketch_hist.hist_update_classes(
                state["pos_hist"], state["neg_hist"], preds, t01 * weight, (1.0 - t01) * weight
            )
            return {"pos_hist": pos_hist, "neg_hist": neg_hist}
        if self.thresholds is None:
            return {"preds": preds, "target": target, "weight": weight}
        return {
            "confmat": state["confmat"]
            + _multilabel_precision_recall_curve_update(
                preds, target, weight, self.num_labels, self.thresholds
            )
        }

    def _curve_state(self, state):
        if self.approx == "sketch":
            tp, fp, tn, fn = _sketch_hist.hist_threshold_counts(
                state["pos_hist"], state["neg_hist"]
            )
            return _counts_to_confmat(tp.T, fp.T, tn.T, fn.T)  # (T, L, 2, 2)
        if self.thresholds is None:
            return (state["preds"], state["target"], state["weight"])
        return state["confmat"]

    def _compute(self, state):
        return _multilabel_precision_recall_curve_compute(
            self._curve_state(state), self.num_labels, self.thresholds, self.ignore_index
        )

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_curve

        curve = curve or self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("Recall", "Precision"))


class PrecisionRecallCurve(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``precision_recall_curve.py:616``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import PrecisionRecallCurve
        >>> metric = PrecisionRecallCurve(task='binary', thresholds=4)
        >>> metric.update(preds, target)
        >>> precision, recall, thresholds = metric.compute()
        >>> np.asarray(precision, np.float64).round(4).tolist()
        [0.5, 0.6667, 1.0, 0.0, 1.0]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryPrecisionRecallCurve(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassPrecisionRecallCurve(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelPrecisionRecallCurve(num_labels, **kwargs)
        raise ValueError(f"Task {task} not supported!")
