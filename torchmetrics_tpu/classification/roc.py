"""Stateful ROC metrics (reference ``src/torchmetrics/classification/roc.py:42,173,339,496``).

Reuses the precision-recall-curve state (reference ``roc.py:40`` does the same) — only
``_compute`` differs.
"""
from __future__ import annotations

from typing import Any, Optional

from torchmetrics_tpu.classification.base import _ClassificationTaskWrapper
from torchmetrics_tpu.classification.precision_recall_curve import (
    BinaryPrecisionRecallCurve,
    MulticlassPrecisionRecallCurve,
    MultilabelPrecisionRecallCurve,
)
from torchmetrics_tpu.functional.classification.precision_recall_curve import Thresholds
from torchmetrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from torchmetrics_tpu.utils.enums import ClassificationTask


class BinaryROC(BinaryPrecisionRecallCurve):
    """Reference ``classification/roc.py:42``.

    Inherits the curve base's state regimes, including the O(1)-state streaming
    ``approx="sketch"`` mode (docs/sketches.md) — the ROC points are then the exact
    curve points at the implicit uniform ``sketch_bins`` grid."""

    def _compute(self, state):
        return _binary_roc_compute(self._curve_state(state), self.thresholds)

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_curve

        curve = curve or self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("False positive rate", "True positive rate"))


class MulticlassROC(MulticlassPrecisionRecallCurve):
    """Reference ``classification/roc.py:173``."""

    def _compute(self, state):
        return _multiclass_roc_compute(
            self._curve_state(state), self.num_classes, self.thresholds, self.average
        )

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_curve

        curve = curve or self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("False positive rate", "True positive rate"))


class MultilabelROC(MultilabelPrecisionRecallCurve):
    """Reference ``classification/roc.py:339``."""

    def _compute(self, state):
        return _multilabel_roc_compute(
            self._curve_state(state), self.num_labels, self.thresholds, self.ignore_index
        )

    def plot(self, curve=None, score=None, ax=None):
        from torchmetrics_tpu.utils.plot import plot_curve

        curve = curve or self.compute()
        return plot_curve(curve, score=score, ax=ax, label_names=("False positive rate", "True positive rate"))


class ROC(_ClassificationTaskWrapper):
    """Task dispatcher (reference ``roc.py:496``).

    Example:
        >>> import numpy as np
        >>> preds = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
        >>> target = np.array([0, 0, 1, 1])
        >>> from torchmetrics_tpu import ROC
        >>> metric = ROC(task='binary', thresholds=4)
        >>> metric.update(preds, target)
        >>> fpr, tpr, thresholds = metric.compute()
        >>> np.asarray(tpr, np.float64).round(4).tolist()
        [0.0, 0.5, 1.0, 1.0]
    """

    def __new__(  # type: ignore[misc]
        cls,
        task: str,
        thresholds: Thresholds = None,
        num_classes: Optional[int] = None,
        num_labels: Optional[int] = None,
        ignore_index: Optional[int] = None,
        validate_args: bool = True,
        **kwargs: Any,
    ):
        task = ClassificationTask.from_str(task)
        kwargs.update({"thresholds": thresholds, "ignore_index": ignore_index, "validate_args": validate_args})
        if task == ClassificationTask.BINARY:
            return BinaryROC(**kwargs)
        if task == ClassificationTask.MULTICLASS:
            if not isinstance(num_classes, int):
                raise ValueError(f"`num_classes` must be `int` but `{type(num_classes)} was passed.`")
            return MulticlassROC(num_classes, **kwargs)
        if task == ClassificationTask.MULTILABEL:
            if not isinstance(num_labels, int):
                raise ValueError(f"`num_labels` must be `int` but `{type(num_labels)} was passed.`")
            return MultilabelROC(num_labels, **kwargs)
        raise ValueError(f"Task {task} not supported!")
