"""torchmetrics_tpu.robust — fault tolerance for the metric engine.

Production-scale metric accumulation fails in three characteristic ways, and this package
owns the defence for each (ISSUE 4; full guide in ``docs/robustness.md``):

- **numeric poisoning** → :mod:`~torchmetrics_tpu.robust.guardrails`: opt-in
  ``Metric(nan_policy=...)`` with in-graph ``jnp.isfinite`` counting/masking and one
  deferred host read at ``compute()`` — never a sync on the update/forward hot path,
- **preemption / crashes** → :mod:`~torchmetrics_tpu.robust.checkpoint`: versioned,
  CRC-checksummed host-side snapshots (``Metric.snapshot()`` / ``Metric.restore()``,
  ``MetricCollection`` round-trip included), crash-consistent against buffer donation
  and buffered accumulation,
- **stragglers / dead peers** → elastic multi-process sync in
  ``torchmetrics_tpu.parallel.sync``: deadline + exponential backoff + retry, quorum
  aggregation over the ranks that DID respond, per-rank health circuit breakers with
  probe/re-admission, and the tri-state ``Metric.world_consistent`` grade
  (``full | quorum | local``),
- **lost epoch tails** → :mod:`~torchmetrics_tpu.robust.journal`: a bounded,
  CRC-checksummed write-ahead journal of update batches between durable snapshots
  (``Metric.journal(dir, every_k)``), so a preempted process restores
  ``snapshot + replay(journal)`` bit-identically,

plus :mod:`~torchmetrics_tpu.robust.chaos` — the deterministic fault-injection harness
(now with composite multi-fault scenarios and the seeded :class:`ChaosMatrix` sweep)
that drives every latch and guard through its failure path (``make chaos`` /
``make chaos-matrix``).
"""
from torchmetrics_tpu.robust import checkpoint, guardrails
from torchmetrics_tpu.robust.checkpoint import (
    accept_reconciliation,
    load_snapshot,
    reconciliation_offer,
    restore_collection,
    restore_metric,
    save_snapshot,
    snapshot_collection,
    snapshot_metric,
)
from torchmetrics_tpu.robust.guardrails import POISON_STATE, POLICIES

__all__ = [
    "POISON_STATE",
    "POLICIES",
    "accept_reconciliation",
    "chaos",
    "checkpoint",
    "guardrails",
    "journal",
    "load_snapshot",
    "reconciliation_offer",
    "restore_collection",
    "restore_metric",
    "save_snapshot",
    "snapshot_collection",
    "snapshot_metric",
]


def __getattr__(name: str):
    # the chaos harness pulls in ops.dispatch; load these lazily so importing the engine
    # (metric.py -> robust.guardrails) never depends on the dispatch layer's import order
    if name in ("chaos", "journal"):
        import importlib

        return importlib.import_module(f"torchmetrics_tpu.robust.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
