"""torchmetrics_tpu.robust — fault tolerance for the metric engine.

Production-scale metric accumulation fails in three characteristic ways, and this package
owns the defence for each (ISSUE 4; full guide in ``docs/robustness.md``):

- **numeric poisoning** → :mod:`~torchmetrics_tpu.robust.guardrails`: opt-in
  ``Metric(nan_policy=...)`` with in-graph ``jnp.isfinite`` counting/masking and one
  deferred host read at ``compute()`` — never a sync on the update/forward hot path,
- **preemption / crashes** → :mod:`~torchmetrics_tpu.robust.checkpoint`: versioned,
  CRC-checksummed host-side snapshots (``Metric.snapshot()`` / ``Metric.restore()``,
  ``MetricCollection`` round-trip included), crash-consistent against buffer donation
  and buffered accumulation,
- **stragglers / dead peers** → bounded multi-process sync in
  ``torchmetrics_tpu.parallel.sync`` (deadline + exponential backoff + retry, degraded
  local-only fallback marked via ``Metric.world_consistent``),

plus :mod:`~torchmetrics_tpu.robust.chaos` — the deterministic fault-injection harness
that drives every latch and guard through its failure path (``make chaos``).
"""
from torchmetrics_tpu.robust import checkpoint, guardrails
from torchmetrics_tpu.robust.checkpoint import (
    restore_collection,
    restore_metric,
    snapshot_collection,
    snapshot_metric,
)
from torchmetrics_tpu.robust.guardrails import POISON_STATE, POLICIES

__all__ = [
    "POISON_STATE",
    "POLICIES",
    "chaos",
    "checkpoint",
    "guardrails",
    "restore_collection",
    "restore_metric",
    "snapshot_collection",
    "snapshot_metric",
]


def __getattr__(name: str):
    # the chaos harness pulls in ops.dispatch; load it lazily so importing the engine
    # (metric.py -> robust.guardrails) never depends on the dispatch layer's import order
    if name == "chaos":
        import importlib

        return importlib.import_module("torchmetrics_tpu.robust.chaos")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
