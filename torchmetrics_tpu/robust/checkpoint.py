"""Durable metric-state snapshots: versioned, CRC-checksummed, host-side blobs.

Long-running multi-host jobs get preempted; a metric accumulated over hours of stream must
survive the restart. ``Metric.state_dict`` (torchmetrics parity) only covers *persistent*
states and carries no integrity information. The snapshot format here is the full-fidelity,
crash-consistent twin:

- **host-side numpy** — every tensor/list state is ``jax.device_get``'ed once, so the blob
  survives buffer donation (device arrays snapshotted at an earlier state generation are
  DELETED by later donated steps; numpy copies are not),
- **structure ("treedef")** — tensor vs list split plus per-entry dtype/shape, validated on
  restore against the receiving metric's registered states,
- **versioned + checksummed** — ``version`` gates format evolution; ``crc`` (zlib.crc32 over
  a canonical byte serialisation of names, dtypes, shapes, and raw array bytes) rejects
  torn/corrupted blobs with a clear :class:`~torchmetrics_tpu.utils.exceptions.SnapshotError`
  instead of silently restoring garbage,
- **crash-consistent against fast dispatch** — snapshotting mid-flight (state buffers
  donated to an in-progress dispatch) or with batches pending in a buffered accumulator
  raises cleanly; the blob records the ``state_generation`` it was taken at.

Blobs are plain dicts of numpy arrays + ints — picklable, ``np.savez``-able, JSON-able
after a base64 hop. See ``docs/robustness.md`` for the format table.
"""
from __future__ import annotations

import zlib
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.utils.exceptions import SnapshotError

FORMAT = "tm-tpu-metric-snapshot"
COLLECTION_FORMAT = "tm-tpu-collection-snapshot"
VERSION = 1


def _canonical_bytes(tensors: Dict[str, np.ndarray], lists: Dict[str, List[np.ndarray]]) -> bytes:
    """Deterministic byte serialisation of the state payload — the CRC input.

    Covers names, kinds, dtypes, shapes, AND raw array bytes, so any bit flip in either
    metadata or data changes the checksum.
    """
    chunks: List[bytes] = []
    for name in sorted(tensors):
        arr = tensors[name]
        chunks.append(f"T:{name}:{arr.dtype.str}:{arr.shape}".encode())
        chunks.append(np.ascontiguousarray(arr).tobytes())
    for name in sorted(lists):
        chunks.append(f"L:{name}:{len(lists[name])}".encode())
        for arr in lists[name]:
            chunks.append(f"E:{arr.dtype.str}:{arr.shape}".encode())
            chunks.append(np.ascontiguousarray(arr).tobytes())
    return b"\x00".join(chunks)


def _checksum(tensors: Dict[str, np.ndarray], lists: Dict[str, List[np.ndarray]]) -> int:
    return zlib.crc32(_canonical_bytes(tensors, lists)) & 0xFFFFFFFF


def snapshot_metric(metric: Any) -> Dict[str, Any]:
    """Build a durable host-side snapshot blob of ``metric``'s full state.

    Raises :class:`SnapshotError` when the state is not readable at a consistent point:
    buffers donated to an in-flight dispatch, or batches pending in a buffered accumulator
    (flush or discard them first — a snapshot must never capture half a window).
    """
    pending = metric.__dict__.get("_buffered_pending", 0)
    if pending:
        raise SnapshotError(
            f"Cannot snapshot {type(metric).__name__}: {pending} batch(es) are pending in a"
            " buffered accumulator, so the state is stale mid-window. Call flush() on the"
            " buffer (or let its context manager exit) before snapshotting."
        )
    state = metric._state
    if state.inflight:
        raise SnapshotError(
            f"Cannot snapshot {type(metric).__name__} mid-flight: the state buffers were"
            " donated to an in-progress dispatch. Snapshot from the training loop, not from"
            " callbacks that run inside a forward step."
        )
    # one batched transfer for the tensor states (device_get of a dict is a single fetch)
    tensors = {k: np.asarray(v) for k, v in jax.device_get(dict(state.tensors)).items()}
    lists = {k: [np.asarray(e) for e in jax.device_get(list(v))] for k, v in state.lists.items()}
    obs.telemetry.counter("robust.snapshots").inc()
    return {
        "format": FORMAT,
        "version": VERSION,
        "class": type(metric).__name__,
        "tensors": tensors,
        "lists": lists,
        "update_count": int(metric._update_count),
        "update_called": bool(metric._update_called),
        "state_generation": int(state.generation),
        "crc": _checksum(tensors, lists),
    }


def _validate_blob(metric: Any, blob: Any) -> None:
    if not isinstance(blob, dict) or blob.get("format") not in (FORMAT,):
        raise SnapshotError(
            f"Not a metric snapshot blob: expected format {FORMAT!r},"
            f" got {blob.get('format') if isinstance(blob, dict) else type(blob).__name__!r}"
        )
    if blob.get("version") != VERSION:
        raise SnapshotError(
            f"Snapshot version mismatch: blob is v{blob.get('version')!r}, this build reads"
            f" v{VERSION}. Re-snapshot with the current build (format evolution is gated on"
            " this field precisely so stale blobs fail loudly)."
        )
    if blob.get("class") != type(metric).__name__:
        raise SnapshotError(
            f"Snapshot was taken from {blob.get('class')!r} but is being restored into"
            f" {type(metric).__name__!r}"
        )
    tensors, lists = blob.get("tensors"), blob.get("lists")
    if not isinstance(tensors, dict) or not isinstance(lists, dict):
        raise SnapshotError("Snapshot blob is missing its tensors/lists payload")
    crc = _checksum(
        {k: np.asarray(v) for k, v in tensors.items()},
        {k: [np.asarray(e) for e in v] for k, v in lists.items()},
    )
    if crc != blob.get("crc"):
        raise SnapshotError(
            f"Snapshot checksum mismatch (stored {blob.get('crc')!r}, computed {crc}):"
            " the blob was corrupted or truncated in storage. Refusing to restore."
        )
    state = metric._state
    if set(tensors) != set(state.tensors) or set(lists) != set(state.lists):
        raise SnapshotError(
            f"Snapshot state names do not match {type(metric).__name__}'s registered states:"
            f" blob has tensors={sorted(tensors)} lists={sorted(lists)}, metric has"
            f" tensors={sorted(state.tensors)} lists={sorted(state.lists)}"
        )
    for name, arr in tensors.items():
        cur = state.tensors[name]
        arr = np.asarray(arr)
        if tuple(arr.shape) != tuple(cur.shape) or np.dtype(arr.dtype) != np.dtype(cur.dtype):
            raise SnapshotError(
                f"Snapshot state {name!r} has shape/dtype {arr.shape}/{arr.dtype}, metric"
                f" expects {tuple(cur.shape)}/{cur.dtype}"
            )


def restore_metric(metric: Any, blob: Dict[str, Any]) -> None:
    """Restore ``metric`` from a :func:`snapshot_metric` blob, after full validation.

    Installs fresh device buffers (never aliases the blob), resets the sync/compute caches,
    and restores the update count so mean-reduce weighting and no-update warnings stay
    correct — bit-identical round-trip across dispatch tiers (jit, AOT+donation, buffered).
    """
    _validate_blob(metric, blob)
    state = metric._state
    for name, arr in blob["tensors"].items():
        # preserve the registered dtype exactly (np round-trips weak-typed scalars wide)
        state.tensors[name] = jnp.asarray(arr, state.tensors[name].dtype)
    for name, entries in blob["lists"].items():
        state.lists[name] = [jnp.asarray(e) for e in entries]
    state.maybe_aliased = True  # fresh uploads may be deduped against live arrays
    state.inflight = False
    metric._update_count = int(blob["update_count"])
    metric._update_called = bool(blob["update_called"])
    metric._computed = None
    metric._cache = None
    metric._is_synced = False
    obs.telemetry.counter("robust.restores").inc()


def snapshot_collection(collection: Any) -> Dict[str, Any]:
    """Snapshot every member of a ``MetricCollection`` under its registration name."""
    blobs = {
        name: snapshot_metric(m)
        for name, m in collection.items(keep_base=True, copy_state=False)
    }
    return {"format": COLLECTION_FORMAT, "version": VERSION, "metrics": blobs}


def restore_collection(collection: Any, blob: Any) -> None:
    """Restore a collection from :func:`snapshot_collection`; members must match by name."""
    if not isinstance(blob, dict) or blob.get("format") != COLLECTION_FORMAT:
        raise SnapshotError(
            f"Not a collection snapshot blob: expected format {COLLECTION_FORMAT!r},"
            f" got {blob.get('format') if isinstance(blob, dict) else type(blob).__name__!r}"
        )
    if blob.get("version") != VERSION:
        raise SnapshotError(
            f"Collection snapshot version mismatch: blob is v{blob.get('version')!r},"
            f" this build reads v{VERSION}"
        )
    members = dict(collection.items(keep_base=True, copy_state=False))
    blobs = blob.get("metrics")
    if not isinstance(blobs, dict) or set(blobs) != set(members):
        got = sorted(blobs) if isinstance(blobs, dict) else blobs
        raise SnapshotError(
            f"Collection snapshot members {got} do not match collection members"
            f" {sorted(members)}"
        )
    for name, m in members.items():
        restore_metric(m, blobs[name])
    # compute-group members alias their leader's arrays; re-establish the aliasing against
    # the freshly restored leader buffers
    if collection._enable_compute_groups and collection._groups_checked:
        collection._state_is_copy = False
        collection._compute_groups_create_state_ref()
