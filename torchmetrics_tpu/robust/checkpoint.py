"""Durable metric-state snapshots: versioned, CRC-checksummed, host-side blobs.

Long-running multi-host jobs get preempted; a metric accumulated over hours of stream must
survive the restart. ``Metric.state_dict`` (torchmetrics parity) only covers *persistent*
states and carries no integrity information. The snapshot format here is the full-fidelity,
crash-consistent twin:

- **host-side numpy** — every tensor/list state is ``jax.device_get``'ed once, so the blob
  survives buffer donation (device arrays snapshotted at an earlier state generation are
  DELETED by later donated steps; numpy copies are not),
- **structure ("treedef")** — tensor vs list split plus per-entry dtype/shape, validated on
  restore against the receiving metric's registered states,
- **versioned + checksummed** — ``version`` gates format evolution; ``crc`` (zlib.crc32 over
  a canonical byte serialisation of names, dtypes, shapes, and raw array bytes) rejects
  torn/corrupted blobs with a clear :class:`~torchmetrics_tpu.utils.exceptions.SnapshotError`
  instead of silently restoring garbage,
- **crash-consistent against fast dispatch** — snapshotting mid-flight (state buffers
  donated to an in-progress dispatch) or with batches pending in a buffered accumulator
  raises cleanly; the blob records the ``state_generation`` it was taken at.

Blobs are plain dicts of numpy arrays + ints — picklable, ``np.savez``-able, JSON-able
after a base64 hop. See ``docs/robustness.md`` for the format table.
"""
from __future__ import annotations

import os
import pickle
import struct
import tempfile
import zlib
from typing import Any, Dict, List, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.utils.exceptions import ReconciliationError, SnapshotError

FORMAT = "tm-tpu-metric-snapshot"
COLLECTION_FORMAT = "tm-tpu-collection-snapshot"
RECONCILIATION_FORMAT = "tm-tpu-reconciliation"
VERSION = 1

#: on-disk container: magic + little-endian (crc32, payload length) + pickled blob
SNAPSHOT_MAGIC = b"TMSNAP1\n"
_DISK_HEADER = struct.Struct("<IQ")


def _canonical_bytes(tensors: Dict[str, np.ndarray], lists: Dict[str, List[np.ndarray]]) -> bytes:
    """Deterministic byte serialisation of the state payload — the CRC input.

    Covers names, kinds, dtypes, shapes, AND raw array bytes, so any bit flip in either
    metadata or data changes the checksum.
    """
    chunks: List[bytes] = []
    for name in sorted(tensors):
        arr = tensors[name]
        chunks.append(f"T:{name}:{arr.dtype.str}:{arr.shape}".encode())
        chunks.append(np.ascontiguousarray(arr).tobytes())
    for name in sorted(lists):
        chunks.append(f"L:{name}:{len(lists[name])}".encode())
        for arr in lists[name]:
            chunks.append(f"E:{arr.dtype.str}:{arr.shape}".encode())
            chunks.append(np.ascontiguousarray(arr).tobytes())
    return b"\x00".join(chunks)


def _checksum(tensors: Dict[str, np.ndarray], lists: Dict[str, List[np.ndarray]]) -> int:
    return zlib.crc32(_canonical_bytes(tensors, lists)) & 0xFFFFFFFF


def snapshot_metric(metric: Any) -> Dict[str, Any]:
    """Build a durable host-side snapshot blob of ``metric``'s full state.

    Raises :class:`SnapshotError` when the state is not readable at a consistent point:
    buffers donated to an in-flight dispatch, or batches pending in a buffered accumulator
    (flush or discard them first — a snapshot must never capture half a window).
    """
    serve_engine = metric.__dict__.get("_serve")
    if serve_engine is not None:
        # quiesce the async ingestion window first: a quiesced snapshot is EXACT over
        # every enqueued batch (docs/serving.md); the mid-flight donation check below
        # stays a hard error — that hazard is intra-dispatch, not window-depth
        serve_engine.quiesce()
    pending = metric.__dict__.get("_buffered_pending", 0)
    if pending:
        raise SnapshotError(
            f"Cannot snapshot {type(metric).__name__}: {pending} batch(es) are pending in a"
            " buffered accumulator, so the state is stale mid-window. Call flush() on the"
            " buffer (or let its context manager exit) before snapshotting."
        )
    state = metric._state
    if state.inflight:
        raise SnapshotError(
            f"Cannot snapshot {type(metric).__name__} mid-flight: the state buffers were"
            " donated to an in-progress dispatch. Snapshot from the training loop, not from"
            " callbacks that run inside a forward step."
        )
    # one batched transfer for the tensor states (device_get of a dict is a single fetch)
    tensors = {k: np.asarray(v) for k, v in jax.device_get(dict(state.tensors)).items()}
    lists = {k: [np.asarray(e) for e in jax.device_get(list(v))] for k, v in state.lists.items()}
    obs.telemetry.counter("robust.snapshots").inc()
    blob = {
        "format": FORMAT,
        "version": VERSION,
        "class": type(metric).__name__,
        "tensors": tensors,
        "lists": lists,
        "update_count": int(metric._update_count),
        "update_called": bool(metric._update_called),
        "state_generation": int(state.generation),
        "crc": _checksum(tensors, lists),
    }
    keys = _keyed_descriptor(metric)
    if keys is not None:
        blob["keys"] = keys
    window = _window_descriptor(metric)
    if window is not None:
        blob["window"] = window
    shard = _shard_descriptor(metric)
    if shard is not None:
        blob["sharding"] = shard
    sketch = _sketch_descriptor(metric)
    if sketch is not None:
        blob["sketch"] = sketch
    return blob


def _sketch_descriptor(metric: Any) -> Any:
    """Per-state sketch descriptors (kind, parameters, error bound) for sketch-backed
    metrics (``torchmetrics_tpu.sketch``), else None.

    Validated on restore BEFORE the shape check: two sketches of different kind or
    capacity can have compatible array shapes but are NOT mergeable states — restoring a
    capacity-64 KLL blob into a capacity-64 count-min (or a different error contract)
    must fail loudly, not corrupt quantiles silently.
    """
    specs = metric.__dict__.get("_sketch_specs")
    if not specs:
        return None
    return {name: spec.describe() for name, spec in specs.items()}


def _shard_descriptor(metric: Any) -> Any:
    """Mesh-placement descriptor of a sharded metric (``Metric.shard``), else None.

    Informational, not validated on restore: the payload is the host-gathered full state
    (``device_get`` of a sharded array assembles every shard), and :func:`restore_metric`
    re-places it under the RECEIVING metric's live mesh — a blob taken on an 8-way mesh
    restores cleanly onto a 4-way (or unsharded) metric and vice versa.
    """
    ctx = metric.__dict__.get("_shard_ctx")
    if ctx is None:
        return None
    specs = metric.__dict__.get("_shard_specs") or {}
    return {
        "mesh": ctx.describe(),
        "specs": {name: str(getattr(s, "spec", s)) for name, s in specs.items()},
    }


def _keyed_descriptor(metric: Any) -> Any:
    """Tenant-axis descriptor for keyed metrics (``torchmetrics_tpu.keyed``), else None.

    The per-key state payload itself rides the ordinary ``tensors`` dict (a keyed state
    IS an ordinary ``[num_keys, ...]`` tensor state, CRC and all); the descriptor pins
    the tenant-axis semantics — key count, template class, routing strategy — so a blob
    can never be restored into a keyed metric of a different key space.
    """
    num_keys = getattr(metric, "num_keys", None)
    template = getattr(metric, "template", None)
    if num_keys is None or template is None:
        return None
    return {
        "num_keys": int(num_keys),
        "template": type(template).__name__,
        "strategy": getattr(metric, "strategy", None),
    }


def _window_descriptor(metric: Any) -> Any:
    """Online-window descriptor (``torchmetrics_tpu.online``), else None.

    The ring payload itself rides the ordinary ``tensors`` dict (``[window, ...]``
    slabs + the slot/count/advance bookkeeping scalars, CRC and all); the descriptor
    pins the window SEMANTICS — geometry, advance cadence, sliding-vs-EMA mode,
    template class — so a blob can never be restored across window shapes. Validated
    BEFORE the shape check: a ring of the same array shapes but a different
    ``advance_every`` is a different state, and must fail loudly.
    """
    desc = getattr(metric, "online_descriptor", None)
    if desc is None:
        return None
    return dict(desc)


def _validate_blob(metric: Any, blob: Any) -> None:
    if not isinstance(blob, dict) or blob.get("format") not in (FORMAT,):
        raise SnapshotError(
            f"Not a metric snapshot blob: expected format {FORMAT!r},"
            f" got {blob.get('format') if isinstance(blob, dict) else type(blob).__name__!r}"
        )
    if blob.get("version") != VERSION:
        raise SnapshotError(
            f"Snapshot version mismatch: blob is v{blob.get('version')!r}, this build reads"
            f" v{VERSION}. Re-snapshot with the current build (format evolution is gated on"
            " this field precisely so stale blobs fail loudly)."
        )
    if blob.get("class") != type(metric).__name__:
        raise SnapshotError(
            f"Snapshot was taken from {blob.get('class')!r} but is being restored into"
            f" {type(metric).__name__!r}"
        )
    tensors, lists = blob.get("tensors"), blob.get("lists")
    if not isinstance(tensors, dict) or not isinstance(lists, dict):
        raise SnapshotError("Snapshot blob is missing its tensors/lists payload")
    crc = _checksum(
        {k: np.asarray(v) for k, v in tensors.items()},
        {k: [np.asarray(e) for e in v] for k, v in lists.items()},
    )
    if crc != blob.get("crc"):
        raise SnapshotError(
            f"Snapshot checksum mismatch (stored {blob.get('crc')!r}, computed {crc}):"
            " the blob was corrupted or truncated in storage. Refusing to restore."
        )
    state = metric._state
    if set(tensors) != set(state.tensors) or set(lists) != set(state.lists):
        raise SnapshotError(
            f"Snapshot state names do not match {type(metric).__name__}'s registered states:"
            f" blob has tensors={sorted(tensors)} lists={sorted(lists)}, metric has"
            f" tensors={sorted(state.tensors)} lists={sorted(state.lists)}"
        )
    expected_keys = _keyed_descriptor(metric)
    if expected_keys is not None:
        keys = blob.get("keys")
        if not isinstance(keys, dict):
            raise SnapshotError(
                f"Snapshot has no tenant-axis descriptor but {type(metric).__name__}"
                f" expects {expected_keys['num_keys']} keys — the blob was taken from an"
                " unkeyed metric."
            )
        if int(keys.get("num_keys", -1)) != expected_keys["num_keys"]:
            raise SnapshotError(
                f"Snapshot holds {keys.get('num_keys')!r} key streams, metric holds"
                f" {expected_keys['num_keys']} — refusing to restore across key spaces."
            )
        if keys.get("template") != expected_keys["template"]:
            raise SnapshotError(
                f"Snapshot keys were accumulated by template {keys.get('template')!r},"
                f" metric's template is {expected_keys['template']!r}"
            )
    expected_window = _window_descriptor(metric)
    if expected_window is not None:
        window = blob.get("window")
        if not isinstance(window, dict):
            raise SnapshotError(
                f"Snapshot has no window descriptor but {type(metric).__name__} is an"
                f" online-window metric ({expected_window['mode']}) — the blob was"
                " taken from a plain (or pre-window) metric."
            )
        if window != expected_window:
            raise SnapshotError(
                f"Snapshot window descriptor {window!r} does not match the metric's"
                f" {expected_window!r} — rings of different geometry, advance cadence,"
                " or decay are not the same state; refusing to restore."
            )
    expected_sketch = _sketch_descriptor(metric)
    if expected_sketch is not None:
        sketch = blob.get("sketch")
        if not isinstance(sketch, dict):
            raise SnapshotError(
                f"Snapshot has no sketch descriptor but {type(metric).__name__} registers"
                f" sketch state(s) {sorted(expected_sketch)} — the blob was taken from a"
                " non-sketch (or pre-sketch) metric."
            )
        for name, want in expected_sketch.items():
            got = sketch.get(name)
            if got != want:
                raise SnapshotError(
                    f"Snapshot sketch state {name!r} was accumulated as {got!r}, metric"
                    f" expects {want!r} — sketches of different kind/capacity/error"
                    " contract are not mergeable states; refusing to restore."
                )
    for name, arr in tensors.items():
        cur = state.tensors[name]
        arr = np.asarray(arr)
        if tuple(arr.shape) != tuple(cur.shape) or np.dtype(arr.dtype) != np.dtype(cur.dtype):
            raise SnapshotError(
                f"Snapshot state {name!r} has shape/dtype {arr.shape}/{arr.dtype}, metric"
                f" expects {tuple(cur.shape)}/{cur.dtype}"
            )


def restore_metric(metric: Any, blob: Dict[str, Any]) -> None:
    """Restore ``metric`` from a :func:`snapshot_metric` blob, after full validation.

    Installs fresh device buffers (never aliases the blob), resets the sync/compute caches,
    and restores the update count so mean-reduce weighting and no-update warnings stay
    correct — bit-identical round-trip across dispatch tiers (jit, AOT+donation, buffered).
    """
    _validate_blob(metric, blob)
    state = metric._state
    shard_specs = metric.__dict__.get("_shard_specs") or {}
    shard_ctx = metric.__dict__.get("_shard_ctx")
    for name, arr in blob["tensors"].items():
        # preserve the registered dtype exactly (np round-trips weak-typed scalars wide)
        value = jnp.asarray(arr, state.tensors[name].dtype)
        spec = shard_specs.get(name)
        if spec is not None:
            # sharded metric: re-place the host payload under the LIVE mesh — the blob
            # carries host-gathered full state, the receiving layout decides placement
            value = jax.device_put(value, spec)
        state.tensors[name] = value
    for name, entries in blob["lists"].items():
        placed = [jnp.asarray(e) for e in entries]
        if shard_ctx is not None:
            placed = [jax.device_put(e, shard_ctx.device_for_entry(i)) for i, e in enumerate(placed)]
        state.lists[name] = placed
    state.maybe_aliased = True  # fresh uploads may be deduped against live arrays
    state.inflight = False
    metric._update_count = int(blob["update_count"])
    metric._update_called = bool(blob["update_called"])
    metric._computed = None
    metric._cache = None
    metric._is_synced = False
    metric.__dict__["_lazy_sync_cache"] = None  # reduce-once cache is per restored epoch
    obs.telemetry.counter("robust.restores").inc()


def snapshot_collection(collection: Any) -> Dict[str, Any]:
    """Snapshot every member of a ``MetricCollection`` under its registration name."""
    blobs = {
        name: snapshot_metric(m)
        for name, m in collection.items(keep_base=True, copy_state=False)
    }
    return {"format": COLLECTION_FORMAT, "version": VERSION, "metrics": blobs}


def restore_collection(collection: Any, blob: Any) -> None:
    """Restore a collection from :func:`snapshot_collection`; members must match by name."""
    if not isinstance(blob, dict) or blob.get("format") != COLLECTION_FORMAT:
        raise SnapshotError(
            f"Not a collection snapshot blob: expected format {COLLECTION_FORMAT!r},"
            f" got {blob.get('format') if isinstance(blob, dict) else type(blob).__name__!r}"
        )
    if blob.get("version") != VERSION:
        raise SnapshotError(
            f"Collection snapshot version mismatch: blob is v{blob.get('version')!r},"
            f" this build reads v{VERSION}"
        )
    members = dict(collection.items(keep_base=True, copy_state=False))
    blobs = blob.get("metrics")
    if not isinstance(blobs, dict) or set(blobs) != set(members):
        got = sorted(blobs) if isinstance(blobs, dict) else blobs
        raise SnapshotError(
            f"Collection snapshot members {got} do not match collection members"
            f" {sorted(members)}"
        )
    for name, m in members.items():
        restore_metric(m, blobs[name])
    # compute-group members alias their leader's arrays; re-establish the aliasing against
    # the freshly restored leader buffers
    if collection._enable_compute_groups and collection._groups_checked:
        collection._state_is_copy = False
        collection._compute_groups_create_state_ref()


# ---------------------------------------------------------------------------
# Durable disk persistence (atomic temp-file + os.replace + fsync)
# ---------------------------------------------------------------------------

def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed file survives power loss (best effort)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform without dir-open (the rename still landed)
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - some filesystems reject dir fsync
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: Union[str, os.PathLike], data: bytes) -> str:
    """Crash-consistent byte write: temp file in the target dir → fsync → ``os.replace``.

    The target path either holds its previous content or the complete new content —
    never a torn intermediate. Shared by snapshot persistence and the update journal.
    """
    path = os.fspath(path)
    directory = os.path.dirname(path) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".tm-tmp-")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(directory)
    return path


def save_snapshot(blob: Dict[str, Any], path: Union[str, os.PathLike]) -> str:
    """Durably persist a :func:`snapshot_metric`/:func:`snapshot_collection` blob to disk.

    The file is written atomically (temp file + ``os.replace`` + fsync of file AND
    directory) so a preemption mid-write leaves either the previous snapshot or the new
    one, never garbage. The container adds an outer CRC over the serialised payload on
    top of the blob's own state CRC; :func:`load_snapshot` validates both layers.

    Automated consumers: :class:`~torchmetrics_tpu.serve.control.DriftSnapshotter` saves
    a ``*-pre.tmsnap``/``*-alarm.tmsnap`` pair through this path the instant a drift
    alarm fires, preserving the state from *before* the distribution moved.
    """
    if not isinstance(blob, dict) or blob.get("format") not in (FORMAT, COLLECTION_FORMAT):
        raise SnapshotError(
            "save_snapshot expects a snapshot blob from Metric.snapshot() /"
            f" MetricCollection.snapshot(); got format"
            f" {blob.get('format') if isinstance(blob, dict) else type(blob).__name__!r}"
        )
    payload = pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL)
    header = SNAPSHOT_MAGIC + _DISK_HEADER.pack(zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
    out = atomic_write_bytes(path, header + payload)
    obs.telemetry.counter("robust.snapshot_saves").inc()
    return out


def load_snapshot(path: Union[str, os.PathLike]) -> Dict[str, Any]:
    """Read a :func:`save_snapshot` file back to a blob, validating the disk container.

    Rejects missing/truncated/corrupted files with :class:`SnapshotError`; the blob's own
    state CRC is re-validated when the blob is restored into a metric.
    """
    path = os.fspath(path)
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except OSError as err:
        raise SnapshotError(f"Cannot read snapshot file {path!r}: {err}") from err
    header_len = len(SNAPSHOT_MAGIC) + _DISK_HEADER.size
    if len(raw) < header_len or not raw.startswith(SNAPSHOT_MAGIC):
        raise SnapshotError(
            f"{path!r} is not a torchmetrics-tpu snapshot file (bad magic/truncated header)"
        )
    crc, length = _DISK_HEADER.unpack(raw[len(SNAPSHOT_MAGIC):header_len])
    payload = raw[header_len:]
    if len(payload) != length:
        raise SnapshotError(
            f"Snapshot file {path!r} is truncated: header promises {length} payload bytes,"
            f" file holds {len(payload)}. Refusing to restore."
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise SnapshotError(
            f"Snapshot file {path!r} failed its container checksum: the file was corrupted"
            " in storage. Refusing to restore."
        )
    blob = pickle.loads(payload)
    if not isinstance(blob, dict) or blob.get("format") not in (FORMAT, COLLECTION_FORMAT):
        raise SnapshotError(f"Snapshot file {path!r} does not contain a snapshot blob")
    return blob


# ---------------------------------------------------------------------------
# Rank re-admission: state reconciliation handshake (docs/robustness.md)
# ---------------------------------------------------------------------------

def reconciliation_offer(
    metric: Any, responding_ranks: Sequence[int] = (), epoch: int = 0
) -> Dict[str, Any]:
    """Build the re-admission handshake blob the quorum side sends a rejoining rank.

    Wraps a full snapshot of ``metric``'s CURRENT state — take the offer while the metric
    is synced (inside ``sync_context``) to ship the quorum's *merged* view — plus the
    ranks that view covers, a caller-defined epoch, and the consistency grade it was
    taken at. The rejoining side validates and applies it with
    :func:`accept_reconciliation`.
    """
    blob = snapshot_metric(metric)
    return {
        "format": RECONCILIATION_FORMAT,
        "version": VERSION,
        "snapshot": blob,
        "responding_ranks": tuple(int(r) for r in responding_ranks),
        "epoch": int(epoch),
        "consistency": str(getattr(metric, "world_consistent", "full")),
    }


def accept_reconciliation(metric: Any, offer: Any, mode: str = "adopt") -> Dict[str, Any]:
    """Apply a re-admission handshake offer on the rejoining rank.

    ``mode="adopt"`` (cold rejoin — the rank's local state is gone): restore the offered
    merged snapshot into ``metric``, making it the rank's state base before it resumes
    contributing. ``mode="verify"`` (warm rejoin — the rank recovered its own state via
    ``snapshot + journal replay``): validate that the offer is structurally compatible
    with the metric (class, state names, shapes, CRC) WITHOUT overwriting the recovered
    local state. Both modes raise :class:`ReconciliationError` on an invalid offer and
    return the offer's metadata (``responding_ranks``, ``epoch``, ``consistency``).
    """
    if not isinstance(offer, dict) or offer.get("format") != RECONCILIATION_FORMAT:
        raise ReconciliationError(
            f"Not a reconciliation offer: expected format {RECONCILIATION_FORMAT!r}, got"
            f" {offer.get('format') if isinstance(offer, dict) else type(offer).__name__!r}"
        )
    if offer.get("version") != VERSION:
        raise ReconciliationError(
            f"Reconciliation version mismatch: offer is v{offer.get('version')!r}, this"
            f" build speaks v{VERSION}"
        )
    snapshot = offer.get("snapshot")
    try:
        if mode == "adopt":
            restore_metric(metric, snapshot)
        elif mode == "verify":
            _validate_blob(metric, snapshot)
        else:
            raise ValueError(f"accept_reconciliation mode must be 'adopt' or 'verify', got {mode!r}")
    except SnapshotError as err:
        raise ReconciliationError(f"Reconciliation offer rejected: {err}") from err
    obs.telemetry.counter("robust.reconciliations").inc()
    obs.telemetry.event(
        "robust.reconciliation", cat="robust",
        args={"mode": mode, "epoch": offer.get("epoch"),
              "responding_ranks": list(offer.get("responding_ranks", ()))},
    )
    return {
        "responding_ranks": tuple(offer.get("responding_ranks", ())),
        "epoch": offer.get("epoch", 0),
        "consistency": offer.get("consistency", "full"),
        "mode": mode,
    }
