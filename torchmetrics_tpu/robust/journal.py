"""Preemption-safe write-ahead journal of metric update batches.

A durable snapshot (``robust/checkpoint.py``) captures the state at one instant; every
update after it dies with the process. On preemptible capacity that tail can be hours of
stream. The journal closes the gap with the classic WAL contract: every update batch is
appended to disk — atomically, checksummed — *before* it is applied, so a preempted
process restores ``snapshot + replay(journal)`` **bit-identically** instead of losing the
tail of the epoch (replay drives the ordinary ``update`` path, which the tier-equivalence
suite proves bit-identical with the jit / AOT+donation / buffered tiers).

Layout of a journal directory::

    <dir>/snapshot.tmsnap      durable state snapshot (atomic, doubly CRC'd)
    <dir>/000000000042.tmj     one record per appended batch, named by sequence number
    <dir>/.writer.lock         O_EXCL exclusive-writer lock: "<pid>:<token>" — a second
                               live MetricJournal on the same dir raises JournalError
                               (two writers interleave sequence numbers silently); a
                               dead holder's lock is stale and stolen with a warning,
                               and recover()/break_lock() force-release it

Record container: ``TMJR1\\n`` magic + little-endian ``(crc32, length)`` + pickled
``{"seq", "args", "kwargs"}`` with every array leaf as host numpy. Records are written
via temp-file + ``os.replace`` + fsync (file and directory), so a record either exists
completely or not at all; a torn TAIL record (a filesystem that lost the rename on power
cut) is skipped with a warning, while corruption anywhere earlier raises
:class:`~torchmetrics_tpu.utils.exceptions.JournalError` — a hole in the middle of the
stream is unrecoverable and must fail loudly.

The journal is **bounded**: :class:`MetricJournal` (``Metric.journal(dir, every_k)``)
takes a durable snapshot every ``every_k`` appends and truncates the replayed prefix, so
disk usage is ``O(every_k)`` batches between snapshots. It also plugs into the dispatch
tiers' buffered seam: ``metric.buffered(k, journal=...)`` (or ``MetricJournal.buffered``)
journals each batch write-ahead at ``update`` time, so batches pending in a
:class:`~torchmetrics_tpu.ops.dispatch.BufferedUpdater` window survive a preemption that
strikes before the flush.
"""
from __future__ import annotations

import os
import pickle
import struct
import uuid
import zlib
from typing import Any, Collection, Dict, Iterator, List, Optional, Tuple, Union

import jax
import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.robust import checkpoint as _checkpoint
from torchmetrics_tpu.utils.exceptions import JournalError
from torchmetrics_tpu.utils.prints import rank_zero_warn

MAGIC = b"TMJR1\n"
RECORD_SUFFIX = ".tmj"
SNAPSHOT_FILENAME = "snapshot.tmsnap"
LOCK_FILENAME = ".writer.lock"
_HEADER = struct.Struct("<IQ")

#: most recent journal activity in this process: the cursor a post-mortem bundle
#: records so replay can stop bit-identically at the captured instant
#: (docs/observability.md "Flight recorder & post-mortem bundles")
_LAST_CURSOR: Optional[Dict[str, Any]] = None


def _note_cursor(path: str, last_seq: int) -> None:
    global _LAST_CURSOR
    _LAST_CURSOR = {
        "path": path,
        "last_seq": int(last_seq),
        "snapshot_present": os.path.exists(os.path.join(path, SNAPSHOT_FILENAME)),
    }


def last_cursor() -> Optional[Dict[str, Any]]:
    """The latest journal cursor this process touched (None before any append)."""
    return None if _LAST_CURSOR is None else dict(_LAST_CURSOR)


def _cursor_seq(cursor: Any) -> Optional[int]:
    """Normalise a replay cursor: int, cursor dict, bundle document, or bundle path."""
    if cursor is None:
        return None
    if isinstance(cursor, int):
        return cursor
    if isinstance(cursor, (str, os.PathLike)):
        from torchmetrics_tpu.obs.bundle import load_bundle

        cursor = load_bundle(cursor, strict=False)
    if isinstance(cursor, dict):
        if "sections" in cursor:  # a full bundle document
            cursor = (cursor["sections"].get("journal") or {}).get("cursor") or {}
        if "last_seq" in cursor:
            return int(cursor["last_seq"])
    raise JournalError(
        f"Unusable journal cursor {cursor!r}: pass a sequence number, a bundle's"
        " journal cursor dict, a bundle document, or a bundle path."
    )


def _pid_alive(pid: int) -> bool:
    """Best-effort liveness probe; a pid we may not signal is assumed alive."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):  # pragma: no cover - exists but not ours
        return True
    return True


class _WriterLock:
    """``O_EXCL`` lockfile guarding a journal dir against a second live writer.

    Two :class:`MetricJournal` proxies appending to one directory would interleave their
    sequence numbers silently — each scans the dir at open and then counts privately, so
    records overwrite or shuffle without any CRC failing. The lockfile holds
    ``"<pid>:<token>"``: a conflicting open raises :class:`JournalError` naming the
    holder's pid; a lock whose holder pid is dead is STALE and stolen with a warning
    (the crashed writer cannot release); release only unlinks when the token still
    matches, so a released-then-stolen lock is never deleted out from under the new
    holder.
    """

    def __init__(self, dirpath: str) -> None:
        self.path = os.path.join(dirpath, LOCK_FILENAME)
        self.token = uuid.uuid4().hex
        self.held = False

    def _read_holder(self) -> Tuple[Optional[int], str]:
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                raw = fh.read().strip()
        except OSError:
            return None, ""
        pid_s, _, token = raw.partition(":")
        try:
            return int(pid_s), token
        except ValueError:
            return None, token

    def acquire(self) -> None:
        payload = f"{os.getpid()}:{self.token}".encode()
        for attempt in (0, 1):
            try:
                fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
            except FileExistsError:
                holder_pid, _ = self._read_holder()
                if attempt == 0 and (holder_pid is None or not _pid_alive(holder_pid)):
                    # the writer died without releasing: steal the stale lock
                    rank_zero_warn(
                        f"Stealing stale journal writer lock {self.path!r}"
                        f" (holder pid {holder_pid} is gone).",
                        UserWarning,
                    )
                    try:
                        os.unlink(self.path)
                    except OSError:  # pragma: no cover - raced another stealer
                        pass
                    continue
                raise JournalError(
                    f"Journal dir {os.path.dirname(self.path)!r} already has a live"
                    f" writer (pid {holder_pid}). Two writers appending to one journal"
                    " interleave records silently; close() the other MetricJournal"
                    " first, or recover()/break_lock() if that process is dead."
                )
            try:
                os.write(fd, payload)
                os.fsync(fd)
            finally:
                os.close(fd)
            self.held = True
            return

    def release(self) -> None:
        if not self.held:
            return
        self.held = False
        holder_pid, token = self._read_holder()
        if holder_pid == os.getpid() and token == self.token:
            try:
                os.unlink(self.path)
            except OSError:  # pragma: no cover - already gone
                pass


def break_lock(path: Union[str, os.PathLike]) -> bool:
    """Force-release a journal dir's writer lock; True when a lock was removed.

    For recovery flows only: calling this asserts the previous writer process is DEAD
    (``recover`` calls it for you). Breaking the lock of a live writer re-opens the
    silent-interleave hazard the lock exists to prevent.
    """
    lock_path = os.path.join(os.fspath(path), LOCK_FILENAME)
    try:
        os.unlink(lock_path)
        return True
    except OSError:
        return False


def _host_tree(value: Any) -> Any:
    """Copy a batch pytree to host numpy (device arrays fetched once, leaves np-ified)."""
    leaves, treedef = jax.tree_util.tree_flatten(value)
    host = [
        np.asarray(leaf) if hasattr(leaf, "shape") or isinstance(leaf, (int, float, bool, complex)) else leaf
        for leaf in jax.device_get(leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, host)


class Journal:
    """Append-only, CRC-checksummed, crash-atomic record log of update batches.

    ``append`` is write-ahead durable: when it returns, the batch is on disk. ``read``
    yields the surviving records in sequence order with full validation.
    ``truncate_through`` drops the prefix a durable snapshot already covers.
    """

    def __init__(self, path: Union[str, os.PathLike], max_pending: int = 65536) -> None:
        self.path = os.fspath(path)
        self.max_pending = int(max_pending)
        os.makedirs(self.path, exist_ok=True)
        existing = self._record_seqs()
        self._next_seq = (existing[-1] + 1) if existing else 0

    # ------------------------------------------------------------------ directory scan
    def _record_seqs(self) -> List[int]:
        seqs = []
        for fname in os.listdir(self.path):
            if fname.endswith(RECORD_SUFFIX) and not fname.startswith("."):
                try:
                    seqs.append(int(fname[: -len(RECORD_SUFFIX)]))
                except ValueError:
                    continue
        return sorted(seqs)

    def _record_path(self, seq: int) -> str:
        return os.path.join(self.path, f"{seq:012d}{RECORD_SUFFIX}")

    @property
    def pending(self) -> int:
        """Records currently on disk (appended since the last truncation)."""
        return len(self._record_seqs())

    @property
    def last_seq(self) -> int:
        """Sequence number of the most recently appended record; -1 before any append."""
        return self._next_seq - 1

    # ------------------------------------------------------------------------- append
    def append(self, args: Tuple = (), kwargs: Optional[Dict[str, Any]] = None) -> int:
        """Durably journal one update batch; returns its sequence number.

        The record is fully on disk (fsync'd, atomically named) before this returns —
        the write-ahead half of the WAL contract. The batch leaves are copied to host
        numpy so later buffer donation cannot invalidate the journaled payload.
        """
        seq = self._next_seq
        payload = pickle.dumps(
            {"seq": seq, "args": _host_tree(tuple(args)), "kwargs": _host_tree(dict(kwargs or {}))},
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        data = MAGIC + _HEADER.pack(zlib.crc32(payload) & 0xFFFFFFFF, len(payload)) + payload
        _checkpoint.atomic_write_bytes(self._record_path(seq), data)
        self._next_seq = seq + 1
        obs.telemetry.counter("robust.journal_appends").inc()
        obs.flightrec.record("journal.append", seq=seq, path=self.path)
        _note_cursor(self.path, seq)
        if self.max_pending and (seq % 64 == 0) and self.pending > self.max_pending:
            rank_zero_warn(
                f"Update journal at {self.path!r} holds {self.pending} records, beyond its"
                f" {self.max_pending}-record bound: no durable snapshot is truncating it."
                " Take snapshots (Metric.journal(every_k=...) does this automatically) or"
                " replay will grow unboundedly expensive.",
                UserWarning,
            )
        return seq

    # --------------------------------------------------------------------------- read
    def _decode(self, seq: int, is_tail: bool) -> Optional[Tuple[int, tuple, dict]]:
        path = self._record_path(seq)
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError as err:
            raise JournalError(f"Cannot read journal record {path!r}: {err}") from err
        header_len = len(MAGIC) + _HEADER.size
        problem = None
        if len(raw) < header_len or not raw.startswith(MAGIC):
            problem = "bad magic/truncated header"
        else:
            crc, length = _HEADER.unpack(raw[len(MAGIC):header_len])
            payload = raw[header_len:]
            if len(payload) != length:
                problem = f"payload truncated ({len(payload)} of {length} bytes)"
            elif zlib.crc32(payload) & 0xFFFFFFFF != crc:
                problem = "checksum mismatch"
        if problem is not None:
            if is_tail:
                # a crash mid-append can only tear the newest record; losing the batch
                # that was being written when the process died is the honest outcome
                obs.flightrec.record("journal.torn_tail", seq=seq, problem=problem)
                rank_zero_warn(
                    f"Journal tail record {path!r} is torn ({problem}); skipping it."
                    " The batch being appended at the crash is not recoverable.",
                    UserWarning,
                )
                return None
            # a mid-stream hole is unrecoverable: bundle the evidence before failing
            obs.flightrec.record("journal.corrupt", seq=seq, problem=problem, path=self.path)
            obs.capture_bundle("journal_corrupt")
            raise JournalError(
                f"Journal record {path!r} is corrupt ({problem}) with later records"
                " present — the stream has a hole and cannot be replayed faithfully."
            )
        rec = pickle.loads(payload)
        if not isinstance(rec, dict) or rec.get("seq") != seq:
            raise JournalError(f"Journal record {path!r} does not match its sequence number")
        return seq, tuple(rec.get("args", ())), dict(rec.get("kwargs", {}))

    def read(self, after_seq: int = -1) -> Iterator[Tuple[int, tuple, dict]]:
        """Yield validated ``(seq, args, kwargs)`` records with ``seq > after_seq``, in order."""
        seqs = [s for s in self._record_seqs() if s > after_seq]
        for i, seq in enumerate(seqs):
            rec = self._decode(seq, is_tail=(i == len(seqs) - 1))
            if rec is not None:
                yield rec

    # ---------------------------------------------------------------------- retention
    def truncate_through(self, seq: int) -> int:
        """Drop records with sequence ≤ ``seq`` (covered by a durable snapshot)."""
        dropped = 0
        for s in self._record_seqs():
            if s <= seq:
                try:
                    os.unlink(self._record_path(s))
                    dropped += 1
                except OSError:  # pragma: no cover - already gone
                    pass
        if dropped:
            _checkpoint._fsync_dir(self.path)
            obs.flightrec.record("journal.truncate", through=seq, dropped=dropped, path=self.path)
            _note_cursor(self.path, self.last_seq)
        return dropped

    def clear(self) -> int:
        """Drop every record (the snapshot file, if any, is left in place)."""
        return self.truncate_through(self._next_seq)


def replay(
    metric: Any,
    journal: Union[Journal, str, os.PathLike],
    after_seq: int = -1,
    through_seq: Optional[int] = None,
    skip_seqs: Optional[Collection[int]] = None,
) -> int:
    """Re-apply journaled batches through ``metric.update``; returns the batch count.

    Replay drives the plain ``update`` path regardless of which dispatch tier originally
    produced the records — the tier-equivalence suite is what makes that bit-identical.
    ``through_seq`` (a post-mortem bundle's journal cursor) stops replay AT that record,
    reconstructing the exact state of the captured instant rather than the journal tail.
    ``skip_seqs`` omits specific records — the WAL journals the *offered* stream at
    enqueue, so replaying an adaptive run bit-identically means skipping exactly the
    sequence numbers the serve controller's decision journal records as shed
    (:func:`torchmetrics_tpu.serve.control.adaptive_recover`).
    """
    jr = journal if isinstance(journal, Journal) else Journal(journal)
    skips = frozenset(int(s) for s in skip_seqs) if skip_seqs else frozenset()
    n = 0
    skipped = 0
    for seq, args, kwargs in jr.read(after_seq=after_seq):
        if through_seq is not None and seq > through_seq:
            break
        if seq in skips:
            skipped += 1
            continue
        metric.update(*args, **kwargs)
        n += 1
    if skipped:
        obs.flightrec.record("journal.replay_skipped", skipped=skipped, path=jr.path)
    if n:
        obs.telemetry.counter("robust.journal_replays").inc(n)
        obs.telemetry.event("robust.journal_replay", cat="robust", args={"batches": n, "path": jr.path})
        obs.flightrec.record(
            "journal.replay", batches=n, path=jr.path,
            through=through_seq if through_seq is not None else jr.last_seq,
        )
    return n


def recover(
    metric: Any, path: Union[str, os.PathLike], cursor: Any = None,
    skip_seqs: Optional[Collection[int]] = None,
) -> Dict[str, Any]:
    """Restore ``snapshot + replay(journal)`` from a journal directory into ``metric``.

    The durable snapshot (if present) is restored first — via the metric's own
    ``restore`` so collections round-trip too — then every journal record past the
    snapshot's high-water mark is replayed. Returns ``{"snapshot_restored", "replayed"}``.

    ``cursor`` accepts a post-mortem bundle's journal cursor — an int sequence number,
    the cursor dict, the loaded bundle document, or a ``.tmb`` path — and stops replay
    at it, so the recovered state is **bit-identical** to the state of the process at
    the instant the bundle was captured (not the journal's later tail). That is the
    post-mortem contract: a bundle plus its journal is a reproducible crash scene.
    """
    path = os.fspath(path)
    through = _cursor_seq(cursor)
    # recovery means the previous writer process is gone — its writer lock (if any) is
    # stale by definition; break it so the recovering process can open a fresh proxy
    break_lock(path)
    jr = Journal(path)
    snap_path = os.path.join(path, SNAPSHOT_FILENAME)
    restored = False
    after = -1
    if os.path.exists(snap_path):
        blob = _checkpoint.load_snapshot(snap_path)
        after = int(blob.pop("journal_seq", -1))
        metric.restore(blob)
        restored = True
    replayed = replay(metric, jr, after_seq=after, through_seq=through, skip_seqs=skip_seqs)
    return {
        "snapshot_restored": restored, "replayed": replayed, "after_seq": after,
        "through_seq": through,
    }


class MetricJournal:
    """Write-ahead journaled proxy for one metric (or collection): ``Metric.journal(...)``.

    Every ``update``/``forward`` appends the batch durably *before* applying it, and
    every ``every_k`` appends a durable snapshot is taken and the journal truncated — the
    bounded snapshot/journal cycle. Use as a context manager::

        with metric.journal("ckpt/m0", every_k=64) as jm:
            for batch in stream:
                jm.update(*batch)          # durable before applied
        # preempted? a fresh process resumes bit-identically:
        with fresh_metric.journal("ckpt/m0", resume=True) as jm:
            ...

    A clean context exit takes a final snapshot; an error exit leaves the journal tail in
    place so recovery still replays the full stream. ``buffered(k)`` returns the target's
    :class:`~torchmetrics_tpu.ops.dispatch.BufferedUpdater` with this journal plugged
    into its write-ahead seam.
    """

    def __init__(
        self,
        metric: Any,
        path: Union[str, os.PathLike],
        every_k: int = 64,
        resume: bool = False,
        max_pending: int = 65536,
    ) -> None:
        if int(every_k) < 1:
            raise ValueError(f"journal(every_k) needs every_k >= 1, got {every_k}")
        self.metric = metric
        self._resume = bool(resume)
        self.recovered: Optional[Dict[str, Any]] = None
        if self._resume:
            # recover() first: it breaks any stale writer lock (the preempted process
            # cannot release) before this proxy takes the exclusive lock below
            self.recovered = recover(self.metric, os.fspath(path))
        self._lock = _WriterLock(os.fspath(path))
        os.makedirs(os.fspath(path), exist_ok=True)
        self._lock.acquire()
        self.journal = Journal(path, max_pending=max_pending)
        self._every_k = int(every_k)
        self._since_snapshot = 0

    @property
    def path(self) -> str:
        return self.journal.path

    def _append(self, args: tuple, kwargs: dict) -> None:
        self.journal.append(args, kwargs)
        self._since_snapshot += 1

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Journal the batch durably, apply it, snapshot/truncate on the ``every_k`` cycle."""
        self._append(args, kwargs)
        self.metric.update(*args, **kwargs)
        self._maybe_checkpoint()

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Journaled twin of ``metric.forward`` (batch value returned as usual)."""
        self._append(args, kwargs)
        value = self.metric.forward(*args, **kwargs)
        self._maybe_checkpoint()
        return value

    __call__ = forward

    def compute(self, *args: Any, **kwargs: Any) -> Any:
        # pure passthrough (reads journal nothing): keeps keyed per-key gathers —
        # ``compute(keys=...)`` — reachable through the journaled proxy
        return self.metric.compute(*args, **kwargs)

    def update_async(self, *args: Any, **kwargs: Any) -> Any:
        """Journaled twin of ``metric.update_async`` (docs/serving.md "WAL contract").

        Wires this journal into the metric's ingestion engine, which appends the batch
        durably at ENQUEUE time — before it is even pending in the window — so a
        preemption mid-overlap recovers ``snapshot + replay`` bit-identically. The
        ``every_k`` snapshot cycle still runs; taking the snapshot quiesces the window
        (a quiesced snapshot is exact).
        """
        eng = self.metric.serve(journal=self.journal)
        if eng.journal is not self.journal:
            raise JournalError(
                "This metric's ingestion engine already journals to a different"
                " directory; one WAL per metric."
            )
        ticket = self.metric.update_async(*args, **kwargs)
        self._since_snapshot += 1
        self._maybe_checkpoint()
        return ticket

    def buffered(self, k: int) -> Any:
        """A :class:`BufferedUpdater` over the target with this journal at its seam."""
        return self.metric.buffered(k, journal=self.journal)

    @staticmethod
    def recover(metric: Any, path: Union[str, os.PathLike], cursor: Any = None) -> Dict[str, Any]:
        """``snapshot + replay(journal)`` into ``metric`` — accepting a post-mortem
        bundle's journal cursor (int / cursor dict / bundle document / ``.tmb`` path)
        so replay stops bit-identically at the captured instant. Delegates to the
        module-level :func:`recover`; provided on the proxy class so recovery code has
        one import surface."""
        return recover(metric, path, cursor=cursor)

    def close(self) -> None:
        """Release the exclusive writer lock (idempotent); the journal stays readable."""
        self._lock.release()

    def __del__(self) -> None:  # pragma: no cover - GC timing
        try:
            self._lock.release()
        except Exception:
            pass

    def _maybe_checkpoint(self) -> None:
        if self._since_snapshot >= self._every_k:
            self.checkpoint()

    def checkpoint(self) -> str:
        """Take a durable snapshot NOW and truncate the journal prefix it covers."""
        blob = self.metric.snapshot()
        blob["journal_seq"] = self.journal.last_seq
        out = _checkpoint.save_snapshot(blob, os.path.join(self.journal.path, SNAPSHOT_FILENAME))
        self.journal.truncate_through(self.journal.last_seq)
        self._since_snapshot = 0
        return out

    def __enter__(self) -> "MetricJournal":
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        # clean exit: consolidate to a snapshot. Error exit: leave the journal tail —
        # the stream is durable either way, and recovery replays it faithfully. The
        # writer lock releases on BOTH paths (the process is alive; an armed lock would
        # block its own next proxy).
        try:
            if exc_type is None:
                self.checkpoint()
        finally:
            self.close()
        return False
