"""In-graph numeric guardrails: ``Metric(nan_policy=...)``.

At production scale the dominant numeric failure is NaN/Inf poisoning: one bad batch
(overflowed loss, a div-by-zero upstream, a corrupted shard) silently contaminates a
sum/mean accumulator and every later ``compute()`` reports garbage. The classic guard —
host-side ``np.isnan`` checks per batch — is exactly what this engine cannot afford: it
forces a device→host sync on the per-step hot path (jaxlint TPU001).

The guardrail here is fully in-graph. When a metric opts in (``nan_policy != "propagate"``)
the engine routes every update through :func:`guarded_update`, which

- counts non-finite values across all floating-point batch leaves with ``jnp.isfinite``
  into an extra ``sum``-reduced state (:data:`POISON_STATE`, registered by the engine), and
- under ``nan_policy="mask"`` additionally replaces non-finite entries with ``0.0``
  before the metric's own ``_update`` sees them.

Both operations are pure jnp and fuse into the same XLA program as the update kernel —
across every dispatch tier (eager jit, AOT+donation, ``update_scan``, buffered). No host
sync happens until ``compute()``, where the engine does ONE deferred ``jax.device_get``
of the poison counter and raises/warns/reports per the policy (see ``Metric._guard_poison``
and ``docs/robustness.md`` for the full policy matrix).

Masking substitutes ``0.0`` — the identity of sums/means, but a value like any other for
order statistics (max/min) and cat states. Metrics that need identity-element NaN handling
(the aggregation stack's ``nan_strategy``) keep their own masking; the policies compose.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

#: name of the in-graph poison-counter state the engine registers when a policy is active.
POISON_STATE = "nan_poison_total"

#: accepted ``nan_policy`` values. "propagate" (default) is a true no-op: no extra state,
#: no wrapper, no per-step cost.
POLICIES = ("propagate", "raise", "warn", "mask")


def validate_policy(policy: Any) -> str:
    if policy not in POLICIES:
        raise ValueError(f"Expected keyword argument `nan_policy` to be one of {POLICIES} but got {policy!r}")
    return policy


def scrub_nonfinite(args: tuple, kwargs: dict, mask: bool) -> Tuple[tuple, dict, Any]:
    """Count (and optionally zero out) non-finite entries across all float batch leaves.

    Returns ``(args, kwargs, bad_count)`` where ``bad_count`` is a float32 scalar (traced
    inside jit, concrete eagerly). Non-float leaves (ints, bools, None, strings) pass
    through untouched — integer arrays cannot hold NaN/Inf.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    bad = jnp.asarray(0.0, jnp.float32)
    out = []
    for leaf in leaves:
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None and jnp.issubdtype(dtype, jnp.floating):
            finite = jnp.isfinite(leaf)
            bad = bad + jnp.sum((~finite).astype(jnp.float32))
            if mask:
                leaf = jnp.where(finite, leaf, jnp.zeros((), dtype))
        out.append(leaf)
    args, kwargs = jax.tree_util.tree_unflatten(treedef, out)
    return args, kwargs, bad


def guarded_update(update_fn: Callable, policy: str) -> Callable:
    """Wrap a metric's ``_update`` with the in-graph poison counter (and mask, if asked).

    The wrapper preserves the functional-core contract — ``(state, *batch) -> state`` —
    and adds :data:`POISON_STATE` to the returned dict when the incoming state carries it
    (fused forward paths hand in the defaults dict, which does). Traced exactly like the
    inner update: zero per-step host work.
    """

    do_mask = policy == "mask"

    def guarded(state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        args, kwargs, bad = scrub_nonfinite(args, kwargs, do_mask)
        out = dict(update_fn(state, *args, **kwargs))
        prev = state.get(POISON_STATE)
        if prev is not None:
            out[POISON_STATE] = prev + bad
        return out

    return guarded
