"""Deterministic fault injection for the metric engine — the chaos harness.

PR 3's fast-dispatch layer grew a set of recovery latches (AOT→jit fallback on compile
failure, defaults-reset on mid-flight donated-dispatch death, buffered-pending guards) and
PR 4 adds more (bounded sync with degraded mode, snapshot/restore). None of them is worth
anything untested: a latch that has never been driven through its failure path is a latch
that fires for the first time in production. This module makes every failure class a
first-class, *seeded* injector:

========================  ============================================================
:class:`AotCompileFailure`  ``aot_compile`` raises → engine must latch broken and fall
                            back to the jit tier with state intact
:class:`DonationHazard`     dispatch dies AFTER donating (state buffers deleted) →
                            engine must reset-to-defaults with an explicit warning;
                            the harness restores the last snapshot and replays
:class:`CollectiveTimeout`  a gather hangs/raises for the first N attempts → bounded
                            sync must retry with backoff, then succeed or degrade
:class:`NaNPoison`          seeded batch elements become NaN/Inf → ``nan_policy`` must
                            count (and under "mask" neutralise) every one in-graph
preemption                  :meth:`ChaosRunner.run` kills the metric instance between
                            steps and restores a fresh one from the snapshot blob
========================  ============================================================

Injectors are context managers patching the REAL seams (``ops.dispatch.aot_compile``,
``ops.dispatch.dispatch_step``, the metric's ``dist_sync_fn``) — no test doubles of the
engine itself. Every firing bumps ``robust.injected_faults``; every absorbed fault bumps
``robust.recovered`` (both embedded in ``obs.bench_extras()``), so a chaos run leaves an
auditable counter trail.

:class:`ChaosRunner` is the reference drive loop: forward a batch stream, snapshot after
every committed step, detect a fault (exception OR the engine's mid-flight reset warning),
restore + replay. Its contract — proven by ``tests/unittests/robust/`` — is that the final
state is **bit-identical** to the unfaulted run for sum/mean/max/min/cat reductions.
"""
from __future__ import annotations

import random
import time
import warnings
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from torchmetrics_tpu import obs
from torchmetrics_tpu.ops import dispatch as _dispatch
from torchmetrics_tpu.utils.prints import reset_warning_cache

#: env knob the chaos CI lane pins (``make chaos``); tests default to it for determinism.
ENV_CHAOS_SEED = "TM_TPU_CHAOS_SEED"
DEFAULT_SEED = 1234


def counters() -> Dict[str, int]:
    """Current chaos/robustness counter values (the ``bench_extras`` trio and friends)."""
    names = (
        "robust.injected_faults",
        "robust.recovered",
        "robust.degraded_syncs",
        "robust.sync_retries",
        "robust.snapshots",
        "robust.restores",
    )
    return {n: obs.telemetry.counter(n).value for n in names}


@contextmanager
def _patched(obj: Any, attr: str, value: Any) -> Iterator[None]:
    original = getattr(obj, attr)
    setattr(obj, attr, value)
    try:
        yield
    finally:
        setattr(obj, attr, original)


class Injector:
    """Base fault injector: a reusable context manager that records firings.

    ``fired`` counts how many times the fault actually triggered inside the ``with`` block;
    each firing bumps the global ``robust.injected_faults`` counter.
    """

    name = "fault"

    def __init__(self) -> None:
        self.fired = 0

    def _fire(self) -> None:
        self.fired += 1
        obs.telemetry.counter("robust.injected_faults").inc()

    def __enter__(self) -> "Injector":  # pragma: no cover - subclasses override
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


class AotCompileFailure(Injector):
    """Force ``aot_compile`` to raise, driving the FastStepCache broken-latch jit fallback.

    Steady-state steps hit cached executables and never reach the compiler, so the
    injector also blanks the cache lookups while armed — the dispatch is forced down the
    build path, where the injected compile failure fires and the engine must latch broken
    and fall back to the jit tier with state intact.
    """

    name = "aot_compile_failure"

    def __enter__(self) -> "AotCompileFailure":
        def boom(*args: Any, **kwargs: Any) -> Any:
            self._fire()
            raise RuntimeError("chaos: injected AOT compile failure")

        self._cms = [
            _patched(_dispatch, "aot_compile", boom),
            _patched(_dispatch.FastStepCache, "fast_entry", lambda cache, treedef: None),
            _patched(_dispatch.FastStepCache, "keyed_entry", lambda cache, key: None),
        ]
        for cm in self._cms:
            cm.__enter__()
        return self

    def __exit__(self, *exc: Any) -> bool:
        for cm in reversed(self._cms):
            cm.__exit__(*exc)
        return False


class DonationHazard(Injector):
    """Kill a fast dispatch AFTER its state buffers were donated.

    Deletes the state leaves (exactly what XLA does to donated inputs) and then raises, so
    the engine's recovery path sees dead buffers and must reset-to-defaults with its
    explicit mid-flight warning — the worst-case donation failure.
    """

    name = "donation_hazard"

    def __enter__(self) -> "DonationHazard":
        def sabotage(cache: Any, builder: Any, state_leaves: Any, *rest: Any) -> Any:
            self._fire()
            for leaf in state_leaves:
                delete = getattr(leaf, "delete", None)
                if callable(delete):
                    delete()
            raise RuntimeError("chaos: injected post-donation dispatch failure")

        self._cm = _patched(_dispatch, "dispatch_step", sabotage)
        self._cm.__enter__()
        return self

    def __exit__(self, *exc: Any) -> bool:
        return self._cm.__exit__(*exc)


class CollectiveTimeout:
    """A ``dist_sync_fn`` whose first ``fail_attempts`` gather calls hang (or raise).

    Drives the bounded-sync deadline/retry/degraded machinery end to end. Not a patcher:
    pass the instance as ``dist_sync_fn=...`` (or ``gather_fn``). ``hang_s=None`` raises a
    ``TimeoutError`` immediately instead of sleeping — faster for retry-path tests.
    """

    def __init__(self, fail_attempts: int = 1, hang_s: Optional[float] = 0.25) -> None:
        self.fail_attempts = fail_attempts
        self.hang_s = hang_s
        self.calls = 0
        self.fired = 0

    def __call__(self, value: Any, group: Any = None, **kwargs: Any) -> List[Any]:
        self.calls += 1
        if self.fired < self.fail_attempts:
            self.fired += 1
            obs.telemetry.counter("robust.injected_faults").inc()
            if self.hang_s is not None:
                time.sleep(self.hang_s)  # outlive the caller's deadline: a straggler peer
                raise TimeoutError("chaos: straggler gather outlived its deadline")
            raise TimeoutError("chaos: injected collective timeout")
        return [value]  # healthy world-of-one gather


class NaNPoison:
    """Seeded NaN/Inf poisoning of a batch stream.

    ``poison(batches)`` returns ``(poisoned, zeroed)`` where ``poisoned`` has a seeded
    subset of float elements replaced by NaN (or ±Inf) and ``zeroed`` is the *reference*
    stream with those same elements replaced by ``0.0`` — exactly what ``nan_policy="mask"``
    must reduce the poisoned stream to, making bit-identical comparison meaningful.
    """

    def __init__(self, seed: int, rate: float = 0.1, values: Sequence[float] = (float("nan"), float("inf"), float("-inf"))) -> None:
        self.rng = random.Random(seed)
        self.rate = rate
        self.values = tuple(values)
        self.poisoned_elements = 0

    def _poison_array(self, arr: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        flat = np.array(arr, dtype=np.float32).reshape(-1)
        zeroed = flat.copy()
        for i in range(flat.size):
            if self.rng.random() < self.rate:
                flat[i] = self.rng.choice(self.values)
                zeroed[i] = 0.0
                self.poisoned_elements += 1
                obs.telemetry.counter("robust.injected_faults").inc()
        return flat.reshape(arr.shape), zeroed.reshape(arr.shape)

    def poison(self, batches: Sequence[Tuple[Any, ...]]) -> Tuple[List[Tuple[Any, ...]], List[Tuple[Any, ...]]]:
        poisoned: List[Tuple[Any, ...]] = []
        zeroed: List[Tuple[Any, ...]] = []
        for batch in batches:
            p_parts, z_parts = [], []
            for part in batch:
                arr = np.asarray(part)
                if np.issubdtype(arr.dtype, np.floating):
                    p, z = self._poison_array(arr)
                else:
                    p = z = arr
                p_parts.append(p)
                z_parts.append(z)
            poisoned.append(tuple(p_parts))
            zeroed.append(tuple(z_parts))
        return poisoned, zeroed


class ChaosRunner:
    """Drive a metric through a batch stream with faults, snapshots, and replay recovery.

    The drive loop is checkpoint-based crash recovery in miniature: snapshot after every
    committed step; when a step faults — an exception escapes, or the engine's
    "failed mid-flight" reset warning fires (state silently back at defaults) — build a
    fresh instance via ``factory`` (the preemption model: the old process is gone), restore
    the last snapshot, and replay the step without the fault. ``via="update"`` drives the
    update/scan tiers instead of per-step forward.
    """

    def __init__(self, factory: Callable[[], Any], seed: Optional[int] = None) -> None:
        self.factory = factory
        self.seed = DEFAULT_SEED if seed is None else seed
        self.rng = random.Random(self.seed)
        self.faults_seen = 0
        self.replays = 0

    def pick_fault_step(self, n_batches: int) -> int:
        """Seeded choice of the step to fault at (never the formation step 0: compute
        groups and the first compile must already exist for the latches to matter)."""
        return self.rng.randrange(1, max(2, n_batches))

    def _step(self, metric: Any, batch: Tuple[Any, ...], via: str) -> None:
        if via == "forward":
            metric(*batch)
        else:
            metric.update(*batch)

    def run(
        self,
        batches: Sequence[Tuple[Any, ...]],
        injector: Optional[Injector] = None,
        fault_steps: Sequence[int] = (),
        preempt_steps: Sequence[int] = (),
        via: str = "forward",
    ) -> Any:
        """Run the stream; returns the final metric instance (compute()-ready)."""
        metric = self.factory()
        snap = metric.snapshot()
        fault_at = set(fault_steps)
        preempt_at = set(preempt_steps)
        for i, batch in enumerate(batches):
            armed = injector is not None and i in fault_at
            faulted = False
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                reset_warning_cache()  # the mid-flight warning is one-shot per process
                try:
                    if armed:
                        with injector:
                            self._step(metric, batch, via)
                    else:
                        self._step(metric, batch, via)
                except Exception:
                    faulted = True
                if any("failed mid-flight" in str(w.message) for w in caught):
                    # the engine absorbed a donated-dispatch death by resetting state to
                    # defaults — usable but WRONG relative to the stream; must replay
                    faulted = True
            if faulted:
                self.faults_seen += 1
                metric = self.factory()
                metric.restore(snap)
                self._step(metric, batch, via)  # replay without the fault
                self.replays += 1
                obs.telemetry.counter("robust.recovered").inc()
            elif armed and getattr(injector, "fired", 0):
                # fault fired but the engine recovered transparently (e.g. AOT latch→jit)
                obs.telemetry.counter("robust.recovered").inc()
            if i in preempt_at:
                # preemption between update and compute: the process dies with only the
                # blob surviving; a fresh instance restores from it
                blob = metric.snapshot()
                metric = self.factory()
                metric.restore(blob)
            snap = metric.snapshot()
        return metric
